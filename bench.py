#!/usr/bin/env python
"""North-star benchmark: cold replay of a ragged event log (BASELINE.md targets).

Structured so the driver's window can NEVER expire with zero data (VERDICT r3 #1):

1. The parent process forces itself onto the host CPU platform (it never touches the
   tunneled TPU backend) and builds the 1M-aggregate / 100M-event counter corpus
   columnar-side, saving it to disk for the replay children.
2. The scalar CPU fold baseline (the reference's Kafka Streams restore is exactly this
   per-aggregate scalar fold, SURVEY.md §3.3) and the phase-2 steady-state command
   latency (p50/p99/commands-per-sec through the full engine with the reference's
   50 ms flush tick and fsync-on-commit FileLog) are measured first — neither needs
   any accelerator.
3. A CPU-JAX replay child measures the batched fold on the host platform and a
   PROVISIONAL result line is printed immediately (platform honestly "cpu").
4. ONE patient TPU attempt runs as a child with the original environment. It is never
   timeout-killed (a killed claimer wedges the axon pool); if it succeeds, the final
   result line is re-emitted with the TPU numbers. Last line wins for the driver.

Prints one JSON line per completed stage to stdout (the last is authoritative):
    {"metric": "cold_replay_events_per_sec", "value": N, "unit": "events/s",
     "vs_baseline": <speedup over the scalar CPU fold>, "platform": ...,
     "pad_ratio": ..., "pack_s": ..., "command_p50_ms": ..., ...}

Env knobs: SURGE_BENCH_AGGREGATES (1_000_000), SURGE_BENCH_EVENTS (100_000_000),
SURGE_BENCH_CPU_SAMPLE (200_000 events), SURGE_BENCH_TIME_CHUNK, SURGE_BENCH_BATCH,
SURGE_BENCH_LATENCY_SECONDS (5; 0 skips phase 2), SURGE_BENCH_LATENCY_WORKERS (64),
SURGE_BENCH_SKIP_CPU_REPLAY (0), SURGE_BENCH_TPU (1; 0 skips the TPU attempt).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


#: the last payload printed to stdout — the terminal failure handler re-emits this
#: (with the error attached) so a late crash can never clobber a measured result
#: with a value-0 line under the driver's last-line-wins parse
_last_printed: dict | None = None


def emit(payload: dict) -> None:
    global _last_printed
    _last_printed = dict(payload)
    print(json.dumps(payload), flush=True)


def _cpu_env(env: dict) -> dict:
    """A copy of ``env`` pinned to the host CPU platform. Unsetting
    PALLAS_AXON_POOL_IPS is required — it is what makes sitecustomize register the
    tunneled backend; JAX_PLATFORMS alone does not prevent the claim."""
    out = dict(env)
    out.pop("PALLAS_AXON_POOL_IPS", None)
    out.pop("AXON_POOL_IPS", None)
    out["JAX_PLATFORMS"] = "cpu"
    return out


# --------------------------------------------------------------------------------------
# corpus on disk (parent writes once; replay children mmap)
# --------------------------------------------------------------------------------------

_CORPUS_FILES = ("agg_idx", "type_ids", "increment_by", "decrement_by",
                 "lengths", "expected_count", "expected_version")


def save_corpus(corpus, root: str) -> None:
    ev = corpus.events
    arrays = {
        "agg_idx": ev.agg_idx, "type_ids": ev.type_ids,
        "increment_by": ev.cols["increment_by"],
        "decrement_by": ev.cols["decrement_by"],
        "lengths": corpus.lengths, "expected_count": corpus.expected_count,
        "expected_version": corpus.expected_version,
    }
    for name in _CORPUS_FILES:
        np.save(os.path.join(root, f"{name}.npy"), arrays[name])


def load_corpus(root: str):
    from surge_tpu.codec.tensor import ColumnarEvents
    from surge_tpu.replay.corpus import CounterCorpus

    a = {name: np.load(os.path.join(root, f"{name}.npy"), mmap_mode="r")
         for name in _CORPUS_FILES}
    events = ColumnarEvents(
        num_aggregates=int(a["lengths"].shape[0]), agg_idx=a["agg_idx"],
        type_ids=a["type_ids"],
        cols={"increment_by": a["increment_by"], "decrement_by": a["decrement_by"]},
        derived_cols={"sequence_number": "ordinal"})
    return CounterCorpus(events=events, lengths=a["lengths"],
                         expected_count=a["expected_count"],
                         expected_version=a["expected_version"])


# --------------------------------------------------------------------------------------
# replay child: one backend, one measured replay, one JSON line on stdout
# --------------------------------------------------------------------------------------

def make_engine():
    """The bench replay engine (shared by parent pack and replay children so
    the wire form and tile plan agree).

    SURGE_BENCH_PROFILE=1 attaches the per-stage replay profiler (a DEBUG
    registry + surge_tpu.replay.profiler): the child payload then carries a
    per-stage encode/h2d/compile/dispatch/fetch breakdown. Off by default —
    the headline numbers always come from the unprofiled hot path."""
    from surge_tpu.config import default_config
    from surge_tpu.models.counter import make_replay_spec
    from surge_tpu.replay.engine import ReplayEngine

    cfg = default_config().with_overrides({
        "surge.replay.batch-size": int(os.environ.get("SURGE_BENCH_BATCH", 8192)),
        # 64 over the old 128: narrower tiles cut time-axis tail padding (pad
        # 1.80 -> 1.47, +8% fold rate at 10M on CPU); the TPU child's smoke
        # sweep overrides with whatever measures best on chip
        "surge.replay.time-chunk": int(os.environ.get("SURGE_BENCH_TIME_CHUNK", 64)),
        "surge.replay.dispatch": os.environ.get("SURGE_BENCH_DISPATCH", "switch"),
        # auto: assoc tree fold for models with an AssociativeFold, dense
        # pre-gathered tiles on accelerators (the r5 on-chip redesign)
        "surge.replay.tile-backend": os.environ.get("SURGE_BENCH_TILE", "auto"),
        "surge.replay.resident-layout": os.environ.get("SURGE_BENCH_LAYOUT",
                                                       "auto"),
        "surge.replay.upload-chunk-mb": int(
            os.environ.get("SURGE_BENCH_UPLOAD_CHUNK_MB", 0)),
        # single corpus, explicit warm: exact buffer length, no bucket padding
        # on the (timed) upload
        "surge.replay.resident-len-bucket": "exact",
    })
    profiler = None
    if os.environ.get("SURGE_BENCH_PROFILE", "0") == "1":
        from surge_tpu.metrics import Metrics, RecordingLevel, engine_metrics
        from surge_tpu.replay.profiler import ReplayProfiler

        registry = Metrics(recording_level=RecordingLevel.DEBUG)
        profiler = ReplayProfiler.if_enabled(registry, engine_metrics(registry))
    return ReplayEngine(make_replay_spec(),
                        config=cfg,
                        unroll=int(os.environ.get("SURGE_BENCH_UNROLL", 1)),
                        profiler=profiler)


def replay_child(corpus_dir: str) -> None:
    import jax

    devices = jax.devices()  # ONE attempt; parent decides platform via env
    platform = devices[0].platform
    log(f"child backend up: platform={platform} devices={devices}")

    # Pre-r5 the smoke sweep ran FIRST to convert a rare claim window into an
    # artifact before betting on full scale. Claims are instant now, and the
    # sweep measurably degrades subsequent uploads in the same process
    # (100 MB put: 0.34 s clean → 3.1 s post-sweep, gc+sync doesn't recover
    # it) — so the full-scale measurement runs on the clean runtime and the
    # sweep banks BENCH_ONCHIP.json AFTERWARDS (see end of this function).
    # Smoke-best knob feedback is retired for the same reason its gating kept
    # rejecting it: smoke rates are latency-floored noise; the auto defaults
    # ARE the measured-best full-scale config.

    from surge_tpu.models.counter import make_replay_spec

    corpus = load_corpus(corpus_dir)
    engine = make_engine()

    # The resident path (default) ships the corpus ONCE (1 byte/event, zero
    # padding on the link) and every fold gathers on-device — the measured
    # time is the flat pack + upload + all folds. Gather programs depend on
    # the buffer's static length, so they are warmed on the REAL buffer with
    # zero-length no-op folds (state untouched) before the timed fold pass.
    # SURGE_BENCH_STREAMING=1 (or the legacy SURGE_BENCH_RESIDENT=0 spelling)
    # falls back to the streaming window path, whose fixed-shape programs ARE
    # warmable corpus-free: one all-padding [width, batch] window per ladder
    # width + the full chunk. (SURGE_BENCH_RESIDENT=1 itself now selects the
    # read-plane fast path in main() and never reaches a replay child.)
    resident_mode = (os.environ.get("SURGE_BENCH_STREAMING", "0") != "1"
                     and os.environ.get("SURGE_BENCH_RESIDENT", "1") == "1")
    bs = engine.batch_size
    if not resident_mode:
        union_cols = {f.name: np.zeros((bs, 1), dtype=f.dtype)
                      for f in make_replay_spec().registry.union_columns()}
        for width in engine.ladder_widths() + [max(engine.time_chunk, 1)]:
            carry = engine._carry_slice(None, 0, bs, bs)
            pad_ids = np.full((bs, width), -1, dtype=np.int32)
            cols = {name: np.zeros((bs, width), dtype=col.dtype)
                    for name, col in union_cols.items()
                    if name not in ("sequence_number",)}
            engine._fold_window(carry, pad_ids, cols, bs,
                                derived_cols={"sequence_number": "ordinal"})
    engine.stats.update(pack_s=0.0, h2d_s=0.0, windows=0)
    warm_compiles = engine.num_compiles()
    log(f"child warmup done, compiled programs: {warm_compiles}")

    extra_timing = {}
    if resident_mode:
        from surge_tpu.replay.engine import ResidentWire

        wire_dir = os.path.join(corpus_dir, "wire")
        stream_segments = int(os.environ.get("SURGE_BENCH_STREAM_SEGMENTS", 0))
        if stream_segments > 1 and os.path.isdir(wire_dir):
            # pipelined mode: upload itself is part of the timed pass (pieces
            # upload while earlier pieces fold); warm with a throwaway pass
            wire = ResidentWire.load(wire_dir)
            engine.replay_resident_streamed(wire, segments=stream_segments)
            # the warm pass uploaded and folded once; count only the timed
            # pass's windows and transfer time
            engine.stats.update(windows=0, h2d_s=0.0, pack_s=0.0)
            warm_compiles = engine.num_compiles()
            log(f"streamed mode ({stream_segments} segments): warmed")
            t0 = time.perf_counter()
            result = engine.replay_resident_streamed(wire,
                                                     segments=stream_segments)
            fold_s = time.perf_counter() - t0
            if engine.num_compiles() != warm_compiles:
                log(f"WARNING: {engine.num_compiles() - warm_compiles} "
                    f"program(s) compiled INSIDE the timed window")
            replay_s = fold_s
            extra_timing = {"fold_s": round(fold_s, 2),
                            "stream_segments": stream_segments}
        else:
            if stream_segments > 1:
                log("streamed mode requested but no packed wire dir exists; "
                    "running the plain resident path")
            t0 = time.perf_counter()
            if os.path.isdir(wire_dir):
                # the parent packed the wire at corpus-build time (the
                # log-segment build analog): cold replay = mmap + upload + fold
                resident = engine.upload_resident(ResidentWire.load(wire_dir))
            else:
                resident = engine.prepare_resident(corpus.events)
            prepare_s = time.perf_counter() - t0
            # compile the single tile program against the real buffers, then
            # run one full throwaway pass: the first real execution pays a
            # one-time runtime/autotune cost (~0.7s measured) that is warmup,
            # not replay — the timed pass still re-uploads its per-replay
            # inputs and re-folds every event
            engine.warm_resident(resident)
            # under the dense layout the warm pass runs the one-time tile
            # gather — a COLD cost, charged to replay_s below
            densify_s = engine.stats["densify_s"]
            engine.replay_resident(resident)
            engine.stats["windows"] = 0  # count only the timed pass's windows
            warm_compiles = engine.num_compiles()
            log(f"resident corpus: {resident.wire_bytes / 1e6:.0f} MB shipped "
                f"in {resident.upload_s:.1f}s; programs warmed + throwaway "
                "pass done")
            t0 = time.perf_counter()
            result = engine.replay_resident(resident)
            fold_s = time.perf_counter() - t0
            if engine.num_compiles() != warm_compiles:
                log(f"WARNING: {engine.num_compiles() - warm_compiles} "
                    f"program(s) compiled INSIDE the timed window (warmup gap)")
            # steady regime: the corpus is resident (standby refresh,
            # repeated rebuilds) — where the accelerator is transfer-free.
            # snapshot the timed pass's window count first so the payload
            # reports it un-inflated by these extra passes
            timed_windows = engine.stats["windows"]
            steady_s = fold_s
            for _ in range(2):
                t0 = time.perf_counter()
                result = engine.replay_resident(resident)
                steady_s = min(steady_s, time.perf_counter() - t0)
            engine.stats["windows"] = timed_windows
            replay_s = prepare_s + densify_s + fold_s
            extra_timing = {"upload_s": round(resident.upload_s, 2),
                            "densify_s": round(densify_s, 2),
                            "fold_s": round(fold_s, 2),
                            "steady_replay_s": round(steady_s, 3),
                            "steady_events_per_sec": round(
                                corpus.num_events / steady_s),
                            "wire_mb": round(resident.wire_bytes / 1e6, 1)}
    else:
        t0 = time.perf_counter()
        result = engine.replay_columnar(corpus.events)
        replay_s = time.perf_counter() - t0
        if engine.num_compiles() != warm_compiles:
            log(f"WARNING: {engine.num_compiles() - warm_compiles} program(s) "
                f"compiled INSIDE the timed window (warmup gap)")

    if not np.array_equal(result.states["count"], corpus.expected_count):
        raise AssertionError("replay count mismatch vs closed-form fold")
    if not np.array_equal(result.states["version"], corpus.expected_version):
        raise AssertionError("replay version mismatch vs closed-form fold")
    if result.num_events != corpus.num_events:
        raise AssertionError("replay event accounting mismatch")

    # Device-resident fold ceiling: re-fold one full window with inputs pinned
    # on device — no host link involved — to separate the DESIGN's TPU fold
    # rate from the tunnel/PCIe transfer bound that governs events_per_sec.
    device_eps = _device_resident_fold_rate(engine, corpus)
    log(f"device-resident fold rate: {device_eps:,.0f} event-slots/s "
        f"(transfer-free)")

    eps = corpus.num_events / replay_s
    payload = {
        "platform": platform,
        "events_per_sec": round(eps),
        "device_fold_events_per_sec": round(device_eps),
        "aggregates_per_sec": round(corpus.num_aggregates / replay_s),
        "replay_s": round(replay_s, 2),
        "pad_ratio": round(result.padded_events / max(corpus.num_events, 1), 3),
        "pack_s": round(engine.stats["pack_s"], 2),
        "h2d_s": round(engine.stats["h2d_s"], 2),
        "windows": engine.stats["windows"],
        "compiles": engine.num_compiles(),
        "num_events": corpus.num_events,
        "num_aggregates": corpus.num_aggregates,
        "knobs": {"dispatch": engine._dispatch, "unroll": engine._unroll,
                  "time_chunk": engine.time_chunk, "batch": engine.batch_size,
                  "tile": engine.tile_backend,
                  "layout": engine._resident_layout,
                  "densify_s": round(engine.stats["densify_s"], 2),
                  "upload_chunk_mb": engine.config.get_int(
                      "surge.replay.upload-chunk-mb", 0)},
        **extra_timing,
    }
    if engine.profiler is not None:
        payload["profile"] = engine.profiler.summary()
        log(f"profile breakdown: {payload['profile']}")
    log(f"child replay: {corpus.num_events:,} events in {replay_s:.2f}s -> "
        f"{eps:,.0f} events/s (pad {payload['pad_ratio']}, pack {payload['pack_s']}s, "
        f"{payload['windows']} windows, {payload['compiles']} programs, verified)")
    print(json.dumps(payload), flush=True)


def _device_resident_fold_rate(engine, corpus) -> float:
    """Slots/s of the compiled fold with every input already on device (carry
    donated and chained): the compute ceiling the replay would reach on a host
    whose link is not the bottleneck."""
    import jax
    import jax.numpy as jnp

    bs = engine.batch_size
    chunk = max(engine.time_chunk, 1)
    key, wire, fold = engine._wire_fold({"sequence_number": "ordinal"})
    ev = corpus.events
    # one full window of real corpus data (batch-major [b, T] densify)
    from surge_tpu.codec.tensor import columnar_to_batch

    sub = ev.sorted_by_aggregate().slice_aggregates(0, min(bs, ev.num_aggregates))
    enc = columnar_to_batch(sub, pad_to=None)
    t = min(enc.max_len, chunk)
    packed, side = wire.pack_window(enc.type_ids, enc.cols, 0, t, chunk, bs)
    packed_dev = jax.device_put(packed)
    side_dev = {k: jax.device_put(v) for k, v in side.items()}
    ord_dev = jax.device_put(np.zeros((bs,), dtype=np.int32))
    def fetch_barrier(c):
        # a real device→host fetch of one element: block_until_ready can
        # return before execution completes on the tunneled relay, and the
        # fetch's data dependency forces the whole chained sequence to finish
        next(iter(np.asarray(v)[:1] for v in c.values()))

    carry = engine._carry_slice(None, 0, bs, bs)
    carry = fold(carry, packed_dev, side_dev, ord_dev)  # warm/compile
    fetch_barrier(carry)
    # calibrate iterations to a ~2s measurement
    t0 = time.perf_counter()
    carry = fold(carry, packed_dev, side_dev, ord_dev)
    fetch_barrier(carry)
    per_iter = max(time.perf_counter() - t0, 1e-5)
    iters = max(int(2.0 / per_iter), 3)
    t0 = time.perf_counter()
    for _ in range(iters):
        carry = fold(carry, packed_dev, side_dev, ord_dev)
    fetch_barrier(carry)
    dt = time.perf_counter() - t0
    return iters * chunk * bs / dt


def run_replay_child(env: dict, corpus_dir: str, label: str) -> dict | None:
    """Run one replay child to completion (NO timeout — a killed claimer wedges the
    axon pool for hours; the driver owns the overall deadline and the provisional
    result line is already on stdout before any TPU attempt starts)."""
    log(f"starting {label} replay child")
    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--replay-child", corpus_dir],
        env=env, stdout=subprocess.PIPE, text=True)
    elapsed = time.perf_counter() - t0
    if proc.returncode != 0:
        log(f"{label} replay child failed rc={proc.returncode} after {elapsed:.0f}s")
        return None
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    log(f"{label} replay child done in {elapsed:.0f}s: "
        f"{out['events_per_sec']:,} events/s on {out['platform']}")
    return out


# --------------------------------------------------------------------------------------
# phase 2: steady-state command latency (no accelerator involved)
# --------------------------------------------------------------------------------------

def steady_state_latency(seconds: float, overrides: dict | None = None,
                         ladder: list | None = None) -> dict:
    """The full command path on one node, reference-default envelope: concurrent
    per-aggregate workers issue sequential Increment commands through
    ``aggregate_for().send_command`` against a FileLog (fsync on commit) with the
    event-driven group-commit publisher, so each command's latency = handling +
    adaptive linger + one durable group-commit transaction — directly comparable
    to the reference's flush-interval + Kafka txn commit envelope (core
    reference.conf:20-21; the fixed 50 ms flush tick this phase used to measure
    is now the `linger_ms=50, max_in_flight=1` row of ``producer_sweep``).

    A WORKER LADDER shows the per-partition group commits breaking past the
    one-command-per-envelope floor (VERDICT r4 weak #3 / next #8): each lane
    commits its accumulated commands in ONE durable txn whose journal fsync is
    shared across lanes (FileLog group-commit round), so commands/s scales
    with concurrency at a near-flat p50 until the host's event loop saturates —
    ``commands_per_txn`` measures the batching directly (journal commits
    counted at the FileLog). ``overrides``/``ladder`` parameterize the
    producer-knob sweep rows."""
    import asyncio
    import shutil
    import tempfile

    from surge_tpu import (
        CommandSuccess,
        SurgeCommandBusinessLogic,
        create_engine,
        default_config,
    )
    from surge_tpu.log.file import FileLog
    from surge_tpu.models import counter

    # server tuning (documented in docs/operations.md): the command path
    # hands off between the event loop, the journal group-sync thread and
    # executor threads constantly; the default 5 ms GIL switch interval turns
    # every handoff into a latency cliff on a busy loop
    sys.setswitchinterval(0.0005)

    base_workers = int(os.environ.get("SURGE_BENCH_LATENCY_WORKERS", 64))
    default_ladder = [base_workers, 256, 1024]
    if ladder is None:
        ladder = []
        for tok in os.environ.get("SURGE_BENCH_LATENCY_LADDER", "").split(","):
            try:
                w = int(tok)
            except ValueError:
                continue  # empty element / typo: skip, never void the phase
            if w > 0:
                ladder.append(w)
    if not ladder:
        ladder = default_ladder
    cfg = default_config()
    if overrides:
        cfg = cfg.with_overrides(overrides)
    flush_ms = cfg.get_int("surge.producer.flush-interval-ms")
    linger_ms = cfg.get_int("surge.producer.linger-ms")
    max_in_flight = cfg.get_int("surge.producer.max-in-flight")
    root = tempfile.mkdtemp(prefix="surge-bench-latency-")

    broker = (overrides or {}).get("bench.broker", "inproc")

    async def scenario() -> dict:
        flog = FileLog(os.path.join(root, "log"), config=cfg)
        journal = flog._journal_path
        log_server = None
        transport = None
        engine_log = flog
        if broker == "grpc":
            # the over-the-wire command path: a loopback LogServer over the
            # same durable FileLog, so max-in-flight's pipelined Transact
            # window (client seq dispatch + broker in-order gate) is actually
            # exercised — in-process logs collapse to one commit in flight
            from surge_tpu.log.client import GrpcLogTransport
            from surge_tpu.log.server import LogServer

            log_server = LogServer(flog, port=0, config=cfg)
            port = log_server.start()
            transport = GrpcLogTransport(f"127.0.0.1:{port}", config=cfg)
            engine_log = transport
        engine = create_engine(
            SurgeCommandBusinessLogic(
                aggregate_name="counter", model=counter.CounterModel(),
                state_format=counter.state_formatting(),
                event_format=counter.event_formatting()),
            log=engine_log, config=cfg)
        await engine.start()

        latencies: list = []

        async def worker(i: int, stop_at: float) -> None:
            agg = f"bench-{i}"
            ref = engine.aggregate_for(agg)
            while time.perf_counter() < stop_at:
                t0 = time.perf_counter()
                r = await ref.send_command(counter.Increment(agg))
                if not isinstance(r, CommandSuccess):
                    raise RuntimeError(f"command failed: {r}")
                latencies.append(time.perf_counter() - t0)

        def journal_commits() -> int:
            with open(journal, "rb") as f:
                return sum(1 for _ in f)

        rungs = []
        for workers in ladder:
            # warmup (entity init + first flushes), then the measured window
            await asyncio.gather(*(worker(i, time.perf_counter() + 1.0)
                                   for i in range(workers)))
            latencies.clear()
            commits0 = journal_commits()
            t0 = time.perf_counter()
            await asyncio.gather(*(worker(i, t0 + seconds)
                                   for i in range(workers)))
            elapsed = time.perf_counter() - t0
            txns = journal_commits() - commits0
            lat_ms = sorted(1000.0 * x for x in latencies)
            n = len(lat_ms)
            rungs.append({
                "workers": workers,
                "commands_per_sec": round(n / elapsed),
                "p50_ms": round(lat_ms[n // 2], 2),
                "p99_ms": round(lat_ms[min(n - 1, (99 * n) // 100)], 2),
                "txn_commits_per_sec": round(txns / elapsed, 1),
                "commands_per_txn": round(n / max(txns, 1), 1),
                "commands": n,
            })
        pstats = engine.producer_stats()
        await engine.stop()
        if transport is not None:
            transport.close()
        if log_server is not None:
            log_server.stop()
        flog.close()

        base = rungs[0]
        return {
            "command_p50_ms": base["p50_ms"],
            "command_p99_ms": base["p99_ms"],
            "commands_per_sec": base["commands_per_sec"],
            "latency_commands": base["commands"],
            "latency_workers": base["workers"],
            "peak_commands_per_sec": max(r["commands_per_sec"] for r in rungs),
            "throughput_ladder": rungs,
            "num_partitions": cfg.get_int("surge.engine.num-partitions"),
            "host_cores": os.cpu_count(),
            "flush_interval_ms": flush_ms,
            "linger_ms": linger_ms,
            "max_in_flight": max_in_flight,
            "broker": broker,
            "producer_stats": pstats,
        }

    try:
        return asyncio.run(scenario())
    finally:
        shutil.rmtree(root, ignore_errors=True)


def native_paired_ladder(seconds: float, rounds: int = 3,
                         rungs=(64, 1024), broker: str = "inproc") -> dict:
    """PAIRED interleaved native-on vs native-off command-path ladder (the
    BENCH_NOTES round-6 protocol: single runs swing 2-3x on this host's 9p
    fsync + 2-vCPU GIL, so only same-host interleaved medians count). Each
    round runs BOTH arms back to back against fresh FileLogs; medians over
    >= 3 rounds per rung decide. The native arm is csrc/txn.cc end to end
    (batch decode + WAL format + staged journal + lazy segments + native
    read decode); the off arm pins surge.log.native.enabled=false AND the
    ambient read-decode switch, i.e. the bit-identical pure-Python path."""
    import statistics as _st

    from surge_tpu.log import native_gate

    arms = {"native_on": True, "native_off": False}
    if not native_gate.available():
        log("native library unbuilt: the on-arm would silently measure the "
            "Python path — run csrc/build.sh first")
    raw: dict = {a: {w: [] for w in rungs} for a in arms}
    for rnd in range(rounds):
        # alternate arm order per round: this host's episodic collapses
        # (CPU steal; BENCH_NOTES round-6) would otherwise bias whichever
        # arm systematically runs adjacent to them
        order = list(arms.items()) if rnd % 2 == 0 else \
            list(arms.items())[::-1]
        for arm, enabled in order:  # interleaved within each round
            native_gate.set_decode_enabled(enabled)
            try:
                stats = steady_state_latency(
                    seconds,
                    overrides={"surge.log.native.enabled": enabled,
                               "bench.broker": broker},
                    ladder=list(rungs))
            finally:
                native_gate.set_decode_enabled(None)
            for rung in stats["throughput_ladder"]:
                raw[arm][rung["workers"]].append(rung)
            log(f"round {rnd + 1}/{rounds} {arm}: " + ", ".join(
                f"{r['workers']}w {r['commands_per_sec']} cmd/s "
                f"p50 {r['p50_ms']}ms"
                for r in stats["throughput_ladder"]))
    med = lambda xs: round(_st.median(xs), 2)  # noqa: E731
    out = {"protocol": {"rounds": rounds, "seconds_per_rung": seconds,
                        "rungs": list(rungs), "broker": broker,
                        "native_available": native_gate.available(),
                        "interleaved": True, "medians": True},
           "rungs": []}
    for w in rungs:
        row = {"workers": w}
        for arm in arms:
            samples = raw[arm][w]
            row[arm] = {
                "commands_per_sec_median": med(
                    [s["commands_per_sec"] for s in samples]),
                "p50_ms_median": med([s["p50_ms"] for s in samples]),
                "p99_ms_median": med([s["p99_ms"] for s in samples]),
                "commands_per_txn_median": med(
                    [s["commands_per_txn"] for s in samples]),
                "rounds": [s["commands_per_sec"] for s in samples],
            }
        off = row["native_off"]["commands_per_sec_median"]
        row["speedup_median"] = round(
            row["native_on"]["commands_per_sec_median"] / max(off, 1), 3)
        out["rungs"].append(row)
        log(f"{w}w medians: native_on "
            f"{row['native_on']['commands_per_sec_median']} cmd/s vs "
            f"native_off {off} cmd/s -> {row['speedup_median']}x")
    return out


def lane_paired_ladder(seconds: float, rounds: int = 3,
                       rungs=(64, 1024), brokers=("inproc", "grpc")) -> dict:
    """PAIRED interleaved command-lane ladder (ISSUE 12, the r08 protocol):
    ``surge.producer.command-lane=direct`` (batch-level ack futures + slim
    timer waits, this PR's lane) vs ``classic`` (the PR-3 per-command
    machinery) — both arms native-on, over the inproc AND grpc rungs, arm
    order alternating per round, medians only (this host's 2-3x run swing,
    BENCH_NOTES round 6)."""
    import statistics as _st

    arms = ("direct", "classic")
    raw: dict = {b: {a: {w: [] for w in rungs} for a in arms}
                 for b in brokers}
    for rnd in range(rounds):
        order = arms if rnd % 2 == 0 else arms[::-1]
        for broker in brokers:
            for arm in order:
                stats = steady_state_latency(
                    seconds,
                    overrides={"surge.producer.command-lane": arm,
                               "bench.broker": broker},
                    ladder=list(rungs))
                for rung in stats["throughput_ladder"]:
                    raw[broker][arm][rung["workers"]].append(rung)
                log(f"round {rnd + 1}/{rounds} {broker}/{arm}: " + ", ".join(
                    f"{r['workers']}w {r['commands_per_sec']} cmd/s "
                    f"p50 {r['p50_ms']}ms"
                    for r in stats["throughput_ladder"]))
    med = lambda xs: round(_st.median(xs), 2)  # noqa: E731
    out = {"protocol": {"rounds": rounds, "seconds_per_rung": seconds,
                        "rungs": list(rungs), "brokers": list(brokers),
                        "interleaved": True, "medians": True},
           "ladders": {}}
    for broker in brokers:
        rows = []
        for w in rungs:
            row = {"workers": w}
            for arm in arms:
                samples = raw[broker][arm][w]
                row[arm] = {
                    "commands_per_sec_median": med(
                        [s["commands_per_sec"] for s in samples]),
                    "p50_ms_median": med([s["p50_ms"] for s in samples]),
                    "p99_ms_median": med([s["p99_ms"] for s in samples]),
                    "rounds": [s["commands_per_sec"] for s in samples],
                }
            base = row["classic"]["commands_per_sec_median"]
            row["speedup_median"] = round(
                row["direct"]["commands_per_sec_median"] / max(base, 1), 3)
            rows.append(row)
            log(f"{broker} {w}w medians: direct "
                f"{row['direct']['commands_per_sec_median']} vs classic "
                f"{base} cmd/s -> {row['speedup_median']}x")
        out["ladders"][broker] = rows
    return out


def resident_feed_paired() -> dict:
    """PAIRED interleaved resident sustained-fold arms (ISSUE 12): the
    native feed (batched JSON decode over native record-index read views)
    vs the per-event Python feed, against the SAME pre-committed FileLog
    tail — the refresh loop refolds it from a 0-anchor per arm, so both
    arms fold identical bytes. Medians over >=3 rounds.

    Knobs: SURGE_BENCH_FEED_EVENTS (40000), _AGGREGATES (2048),
    _ROUNDS (3), _PARTITIONS (4), _MAX_POLL (8192)."""
    import asyncio
    import statistics as _st

    from surge_tpu.config import default_config
    from surge_tpu.log import LogRecord, TopicSpec
    from surge_tpu.log import native_gate
    from surge_tpu.log.file import FileLog
    from surge_tpu.models import counter
    from surge_tpu.replay.resident_state import ResidentStatePlane
    from surge_tpu.serialization import SerializedMessage

    import shutil
    import tempfile

    fold_events = int(os.environ.get("SURGE_BENCH_FEED_EVENTS", 40_000))
    n_agg = int(os.environ.get("SURGE_BENCH_FEED_AGGREGATES", 2048))
    rounds = max(int(os.environ.get("SURGE_BENCH_FEED_ROUNDS", 3)), 1)
    nparts = int(os.environ.get("SURGE_BENCH_FEED_PARTITIONS", 4))
    max_poll = int(os.environ.get("SURGE_BENCH_FEED_MAX_POLL", 8192))
    evt_fmt = counter.event_formatting()
    aggs = [f"agg-{i}" for i in range(n_agg)]

    root = tempfile.mkdtemp(prefix="surge-bench-feed-")
    flog = FileLog(os.path.join(root, "log"), config=default_config())
    flog.create_topic(TopicSpec("events", nparts))
    prod = flog.transactional_producer("feed-bench")
    seqs = {a: 0 for a in aggs}
    prod.begin()
    for i in range(fold_events):
        a = aggs[(i * 7919) % n_agg]
        seqs[a] += 1
        prod.send(LogRecord(
            topic="events", key=a,
            value=evt_fmt.write_event(
                counter.CountIncremented(a, 1, seqs[a])).value,
            partition=hash(a) % nparts))
        if i % 5000 == 4999:
            prod.commit()
            prod.begin()
    prod.commit()

    def one_arm(native_feed: bool) -> float:
        native_gate.set_decode_enabled(native_feed)

        async def scenario() -> float:
            cfg = default_config().with_overrides({
                "surge.replay.resident.capacity": max(n_agg, 8),
                "surge.replay.resident.refresh-interval-ms": 10,
                "surge.replay.resident.refresh-max-poll-records": max_poll,
                "surge.replay.resident.native-feed": native_feed,
            })
            plane = ResidentStatePlane(
                flog, "events", counter.make_replay_spec(), config=cfg,
                partitions=[],  # no seed; the refresh loop refolds from 0
                deserialize_event=lambda b: evt_fmt.read_event(
                    SerializedMessage(key="", value=b)),
                deserialize_events=evt_fmt.read_events_batch,
                serialize_state=lambda a, s: b"")
            await plane.start()
            t0 = time.perf_counter()
            plane.set_partitions(list(range(nparts)))
            while plane.lag_records() > 0:
                await asyncio.sleep(0.005)
            rate = plane.stats["folded_events"] / (time.perf_counter() - t0)
            await plane.stop()
            return rate

        try:
            return asyncio.run(scenario())
        finally:
            native_gate.set_decode_enabled(None)

    raw = {"native_feed": [], "python_feed": []}
    try:
        one_arm(True)  # warmup: compile the fold programs outside the rounds
        for rnd in range(rounds):
            order = (("native_feed", True), ("python_feed", False))
            if rnd % 2:
                order = order[::-1]
            for name, enabled in order:
                rate = one_arm(enabled)
                raw[name].append(round(rate))
                log(f"feed round {rnd + 1}/{rounds} {name}: "
                    f"{rate:,.0f} ev/s sustained")
    finally:
        flog.close()
        shutil.rmtree(root, ignore_errors=True)
    nat = _st.median(raw["native_feed"])
    pyf = _st.median(raw["python_feed"])
    return {"protocol": {"rounds": rounds, "fold_events": fold_events,
                        "aggregates": n_agg, "partitions": nparts,
                        "max_poll": max_poll, "interleaved": True,
                        "medians": True,
                        "native_available": native_gate.available()},
            "native_feed_events_per_sec_median": round(nat),
            "python_feed_events_per_sec_median": round(pyf),
            "speedup_median": round(nat / max(pyf, 1), 3),
            "rounds": raw}


def views_paired() -> dict:
    """PAIRED interleaved view-read vs scan-per-read reader ladder (ISSUE
    17): N concurrent readers all want the SAME grouped-aggregate answer —
    arm A reads the materialized view the resident plane keeps folded (one
    host merge of per-partition partials per read), arm B answers each read
    with a from-scratch query-engine scan of the same committed events (the
    batch ``query()`` path, pre-encoded so the scan arm pays no segment IO).
    Both arms run back to back per round against the same corpus in the same
    process, order alternating per round; medians only.

    Knobs: SURGE_BENCH_VIEWS_EVENTS (50000), _AGGREGATES (1024),
    _ROUNDS (3), _PARTITIONS (4), _LADDER (16,64,256,1024)."""
    import asyncio
    import statistics as _st

    from surge_tpu.codec.tensor import encode_events_columnar
    from surge_tpu.config import default_config
    from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
    from surge_tpu.models import counter
    from surge_tpu.replay.query import Aggregate, QueryEngine, ScanQuery
    from surge_tpu.replay.resident_state import ResidentStatePlane
    from surge_tpu.replay.views import MaterializedViews, ViewDef
    from surge_tpu.serialization import SerializedMessage

    n_events = int(os.environ.get("SURGE_BENCH_VIEWS_EVENTS", 50_000))
    n_agg = int(os.environ.get("SURGE_BENCH_VIEWS_AGGREGATES", 1024))
    rounds = max(int(os.environ.get("SURGE_BENCH_VIEWS_ROUNDS", 3)), 1)
    nparts = int(os.environ.get("SURGE_BENCH_VIEWS_PARTITIONS", 4))
    ladder = [int(t) for t in os.environ.get(
        "SURGE_BENCH_VIEWS_LADDER", "16,64,256,1024").split(",") if t]

    evt_fmt = counter.event_formatting()
    spec = counter.make_replay_spec()
    aggs = [f"agg-{i}" for i in range(n_agg)]
    query = ScanQuery(aggregates=(Aggregate("count"),
                                  Aggregate("sum", "increment_by"),
                                  Aggregate("max", "sequence_number")))

    mlog = InMemoryLog()
    mlog.create_topic(TopicSpec("events", nparts))
    prod = mlog.transactional_producer("views-bench")
    prod.begin()
    seqs = {a: 0 for a in aggs}
    by_agg: dict = {}
    for i in range(n_events):
        a = aggs[(i * 7919) % n_agg]
        seqs[a] += 1
        ev = counter.CountIncremented(a, 1, seqs[a])
        by_agg.setdefault(a, []).append(ev)
        prod.send(LogRecord(topic="events", key=a,
                            value=evt_fmt.write_event(ev).value,
                            partition=hash(a) % nparts))
    prod.commit()

    # arm B's corpus: the identical committed events as one columnar chunk
    colev = encode_events_columnar(spec.registry, list(by_agg.values()))
    colev.aggregate_ids = list(by_agg)
    qe = QueryEngine(spec, config=default_config())

    async def scenario() -> dict:
        cfg = default_config().with_overrides({
            "surge.replay.resident.capacity": max(n_agg, 8),
            "surge.replay.resident.refresh-interval-ms": 10,
        })
        plane = ResidentStatePlane(
            mlog, "events", spec, config=cfg,
            deserialize_event=lambda b: evt_fmt.read_event(
                SerializedMessage(key="", value=b)),
            serialize_state=lambda a, s: b"")
        views = MaterializedViews(spec, config=cfg)
        plane.attach_views(views)
        plane.register_view(ViewDef(name="totals", query=query))
        await plane.start()
        while plane.lag_records() > 0:
            await asyncio.sleep(0.005)
        loop = asyncio.get_running_loop()

        def view_read():
            views.snapshot("totals")

        def scan_read():
            qe.scan_chunks([colev], query)

        async def arm(n_readers: int, fn) -> float:
            t0 = time.perf_counter()
            await asyncio.gather(*(loop.run_in_executor(None, fn)
                                   for _ in range(n_readers)))
            return n_readers / (time.perf_counter() - t0)

        view_read()
        scan_read()  # warmup: compile/cache both read paths off the clock
        rungs = []
        try:
            for n in ladder:
                raw = {"view_read": [], "scan_per_read": []}
                for rnd in range(rounds):
                    order = (("view_read", view_read),
                             ("scan_per_read", scan_read))
                    if rnd % 2:
                        order = order[::-1]
                    for name, fn in order:
                        raw[name].append(round(await arm(n, fn), 1))
                view = _st.median(raw["view_read"])
                scan = _st.median(raw["scan_per_read"])
                rungs.append({
                    "readers": n,
                    "view_read": {"reads_per_sec_median": view,
                                  "rounds": raw["view_read"]},
                    "scan_per_read": {"reads_per_sec_median": scan,
                                      "rounds": raw["scan_per_read"]},
                    "speedup_median": round(view / max(scan, 1e-9), 3)})
                log(f"{n} readers medians: view {view:,.0f} reads/s, "
                    f"scan-per-read {scan:,.0f} reads/s "
                    f"({rungs[-1]['speedup_median']}x)")
        finally:
            await plane.stop()
        return {"protocol": {"events": n_events, "aggregates": n_agg,
                             "partitions": nparts, "rounds": rounds,
                             "interleaved": True, "medians": True},
                "rungs": rungs}

    return asyncio.run(scenario())


def anatomy_bench() -> dict:
    """SURGE_BENCH_ANATOMY=1: traced command phase → the per-leg critical-path
    attribution table alongside the phase's latency medians (ISSUE 14).

    One engine drives a FileLog-backed gRPC broker with tracing + tail
    sampling wired on BOTH sides (tail latency threshold 0: every completed
    trace is kept, budget raised accordingly), closed-loop workers send
    commands for a few seconds, then both trace rings are dumped, assembled
    across the process boundary and attributed. Reported:

    - ``command_p50_ms`` / ``command_p99_ms`` — the phase's command-latency
      medians (same closed-loop shape as the ladder arms, so the table reads
      against numbers of the usual kind);
    - ``anatomy`` — the attribution table (per-leg p50/p99/total/share);
    - ``anatomy_dominant`` / ``anatomy_dominant_share`` — where the time
      went. The next perf PR starts from this, not from guesses.

    Env: SURGE_BENCH_ANATOMY_SECONDS (3), SURGE_BENCH_ANATOMY_WORKERS (16).
    """
    import asyncio
    import socket
    import tempfile

    from surge_tpu import SurgeCommandBusinessLogic, create_engine
    from surge_tpu.config import Config
    from surge_tpu.log import GrpcLogTransport, LogServer
    from surge_tpu.log.file import FileLog
    from surge_tpu.models import counter
    from surge_tpu.observability.anatomy import (assemble_traces,
                                                 attribution_table)
    from surge_tpu.tracing import Tracer

    seconds = float(os.environ.get("SURGE_BENCH_ANATOMY_SECONDS", 3.0))
    workers = int(os.environ.get("SURGE_BENCH_ANATOMY_WORKERS", 16))
    cfg = Config(overrides={
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.engine.num-partitions": 4,
        "surge.trace.tail.latency-ms": 0,       # keep every completed trace
        "surge.trace.tail.keep-budget": 100_000,
        "surge.trace.ring-capacity": 4096,
    })
    logic = SurgeCommandBusinessLogic(
        aggregate_name="anatomy", model=counter.CounterModel(),
        state_format=counter.state_formatting(),
        event_format=counter.event_formatting())
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    tmp = tempfile.mkdtemp(prefix="surge-anatomy-")
    broker_tracer = Tracer(service="broker")
    server = LogServer(FileLog(os.path.join(tmp, "log"), fsync="commit",
                               config=cfg),
                       port=port, config=cfg, tracer=broker_tracer)
    server.start()
    engine_tracer = Tracer(service="engine")
    log = GrpcLogTransport(f"127.0.0.1:{port}", config=cfg,
                           tracer=engine_tracer)
    latencies: list = []

    async def phase() -> None:
        engine = create_engine(logic, log=log, config=cfg,
                               tracer=engine_tracer)
        await engine.start()
        deadline = time.monotonic() + seconds

        async def worker(i: int) -> None:
            ref = engine.aggregate_for(f"agg{i}")
            while time.monotonic() < deadline:
                t0 = time.perf_counter()
                await ref.send_command(counter.Increment(f"agg{i}"))
                latencies.append((time.perf_counter() - t0) * 1000.0)

        await asyncio.gather(*(worker(i) for i in range(workers)))
        await engine.stop()
        # the rings belong to the tracers, which outlive the engine: dump
        # after stop so in-flight flush spans have finished
        self_dump = engine.trace_ring.dump()
        stats["engine_dump"] = self_dump

    stats: dict = {}
    try:
        asyncio.run(phase())
    finally:
        broker_dump = (server.trace_ring.dump()
                       if server.trace_ring is not None else {"traces": []})
        server.stop()
    latencies.sort()

    def pct(q: float) -> float:
        if not latencies:
            return 0.0
        return latencies[min(int(q * (len(latencies) - 1)), len(latencies) - 1)]

    table = attribution_table(assemble_traces(
        [stats.get("engine_dump", {"traces": []}), broker_dump]))
    return {"anatomy_commands": len(latencies),
            "command_p50_ms": round(pct(0.50), 3),
            "command_p99_ms": round(pct(0.99), 3),
            "anatomy": table["legs"],
            "anatomy_traces": table["traces"],
            "anatomy_dominant": table["dominant"],
            "anatomy_dominant_share": table["dominant_share"]}


def failover_bench() -> dict:
    """SURGE_BENCH_FAILOVER=1: kill the replicated log leader under load and
    measure the unavailability window while PROVING zero-loss/zero-duplicate
    delivery (docs/operations.md failover runbook).

    A leader⇄follower broker pair runs with auto-promotion armed; worker
    threads drive sequential commits through the publisher-protocol retry
    ladder (verbatim retry, reopen-on-fence — the txn-seq dedup window owns
    exactly-once); mid-run the leader is hard-killed. Reported:

    - ``failover_unavailability_ms`` — the longest gap between consecutive
      successful acks across all workers (the outage the client actually saw);
    - ``acked_commits`` / ``lost`` / ``duplicated`` — ledger vs the promoted
      leader's log (both MUST be 0);
    - ``failover_timeline`` — the machine-readable failover story merged from
      BOTH brokers' flight recorders (host-monotonic timestamps): promotion
      decision → promotion → fence → truncation → first acked post-failover
      commit. The fence/truncation legs come from restarting the killed
      ex-leader against the new leader after the load phase — the full
      KIP-101 rejoin, reconstructed without reading a single log line.

    Env: SURGE_BENCH_FAILOVER_WORKERS (16), SURGE_BENCH_FAILOVER_SECONDS (6;
    the kill lands ~40% in)."""
    import threading

    from surge_tpu.config import Config
    from surge_tpu.log import (GrpcLogTransport, InMemoryLog, LogRecord,
                               LogServer, TopicSpec)
    from surge_tpu.log.transport import NotLeaderError, ProducerFencedError

    workers = int(os.environ.get("SURGE_BENCH_FAILOVER_WORKERS", 16))
    seconds = float(os.environ.get("SURGE_BENCH_FAILOVER_SECONDS", 6.0))
    cfg = Config(overrides={
        "surge.log.replication-ack-timeout-ms": 1_500,
        "surge.log.replication-isr-timeout-ms": 2_000,
        "surge.log.failover.probe-interval-ms": 150,
        "surge.log.failover.probe-failures": 2,
    })
    lport, fport = _free_ports(2)
    follower = LogServer(InMemoryLog(), port=fport,
                         follower_of=f"127.0.0.1:{lport}", auto_promote=True,
                         config=cfg)
    follower.start()
    leader = LogServer(InMemoryLog(), port=lport,
                       replicate_to=[f"127.0.0.1:{fport}"], config=cfg)
    leader.start()
    targets = f"127.0.0.1:{lport},127.0.0.1:{fport}"
    setup = GrpcLogTransport(targets, config=cfg)
    setup.create_topic(TopicSpec("ev", 1))

    stop_at = time.monotonic() + seconds
    kill_at = time.monotonic() + 0.4 * seconds
    acked_lock = threading.Lock()
    acked: list = []          # payloads acked to the "user"
    ack_times: list = []      # monotonic stamps of every successful ack

    def worker(w: int) -> None:
        client = GrpcLogTransport(targets, config=cfg)
        producer = None
        i = 0
        try:
            while time.monotonic() < stop_at:
                payload = f"w{w}-{i}".encode()
                deadline = time.monotonic() + 30.0
                while True:
                    try:
                        if producer is None:
                            producer = client.transactional_producer(
                                f"bench-fo-{w}")
                        producer.begin()
                        producer.send(LogRecord(topic="ev", key=f"w{w}",
                                                value=payload, partition=0))
                        producer.commit()
                        break
                    except (ProducerFencedError, NotLeaderError):
                        producer = None
                    except Exception:  # noqa: BLE001 — broker mid-failover
                        if producer is not None and producer.in_transaction:
                            producer.abort()
                        time.sleep(0.05)
                    if time.monotonic() > deadline:
                        return  # counted as in-doubt, never acked
                with acked_lock:
                    acked.append(payload)
                    ack_times.append(time.monotonic())
                i += 1
        finally:
            client.close()

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(workers)]
    for t in threads:
        t.start()
    killed_at = None
    while time.monotonic() < stop_at:
        if killed_at is None and time.monotonic() >= kill_at:
            leader.kill()
            killed_at = time.monotonic()
            log("failover bench: leader killed")
        time.sleep(0.02)
    for t in threads:
        t.join(60.0)

    if killed_at is not None:
        deadline = time.monotonic() + 30
        while follower.role != "leader" and time.monotonic() < deadline:
            time.sleep(0.02)
    # rejoin leg: restart the killed ex-leader (same inner log, same flight
    # recorder) against the new leader — its split-brain guard finds the
    # higher epoch BEFORE serving, records the fence, truncates the divergent
    # tail and catches up, completing the flight-recorded failover story
    relit = None
    if killed_at is not None and follower.role == "leader":
        if leader.kill_done is not None:
            leader.kill_done.wait(10)
        try:
            relit = LogServer(leader.log, port=lport,
                              replicate_to=[f"127.0.0.1:{fport}"],
                              flight=leader.flight, config=cfg)
            relit.start()
            deadline = time.monotonic() + 20
            while relit.role != "follower" and time.monotonic() < deadline:
                time.sleep(0.05)
        except Exception as exc:  # noqa: BLE001 — timeline then incomplete
            log(f"failover bench: ex-leader rejoin failed: {exc!r}")
    # unavailability: the longest gap between consecutive acks anywhere
    # (covers the kill → promotion → first post-failover ack window)
    gaps = [b - a for a, b in zip(ack_times, ack_times[1:])]
    unavailability_ms = round(max(gaps) * 1000.0, 1) if gaps else None
    present: dict = {}
    for r in follower.log.read("ev", 0):
        present[r.value] = present.get(r.value, 0) + 1
    lost = sum(1 for p in acked if present.get(p, 0) == 0)
    duplicated = sum(1 for p in acked if present.get(p, 0) > 1)
    # the failover timeline, reconstructed from both brokers' black boxes
    from surge_tpu.observability import merge_dumps, reconstruct_failover

    dumps = [leader.flight.dump(), follower.flight.dump()]
    merged = merge_dumps(dumps)
    recon = reconstruct_failover(merged)
    setup.close()
    if relit is not None:
        relit.stop()
    leader.stop()
    follower.stop()
    out = {
        "failover_unavailability_ms": unavailability_ms,
        "acked_commits": len(acked),
        "lost": lost,
        "duplicated": duplicated,
        "promoted": follower.role == "leader",
        "epoch": follower.epoch,
        "workers": workers,
        "seconds": seconds,
        "failover_timeline": {
            "events": merged,
            "phases": recon["phases"],
            "complete": recon["complete"],
            "decision_to_first_ack_ms": recon["span_ms"],
        },
    }
    if lost or duplicated:
        out["FAILED"] = "acked-record loss or duplication detected"
    log(f"failover bench: {len(acked)} acked, lost={lost} "
        f"duplicated={duplicated}, unavailability "
        f"{unavailability_ms}ms, promoted={out['promoted']}, "
        f"timeline complete={recon['complete']} "
        f"(decision->first-ack {recon['span_ms']}ms)")
    return out


def soak_bench() -> dict:
    """SURGE_BENCH_SOAK=1: the sustained self-healing soak
    (surge_tpu.cluster.soak) across several seeded chaos schedules on a
    3+-broker spread cluster — rolling kills (coordinator on odd seeds,
    partition leaders on even), seeded link faults, AddBroker/RemoveBroker
    churn, Zipf hot-key skew — each scored by a federated scrape + the SLO
    burn-rate engine and the autobalancer driving planned per-partition
    handoffs.

    Env: SURGE_BENCH_SOAK_SEEDS (comma list; default 41,42,43,44),
    SURGE_BENCH_SOAK_SECONDS (12 per seed), SURGE_BENCH_SOAK_BROKERS (3),
    SURGE_BENCH_SOAK_PARTITIONS (6), SURGE_BENCH_SOAK_WRITERS (4).

    The verdict aggregates every seed: total acked commits, 0 lost / 0
    duplicated, exactly-one-leader-per-partition convergence, every SLO page
    cleared, and the autobalancer decision/move counts from the merged
    flight timelines."""
    from surge_tpu.cluster.soak import run_soak

    seeds = [int(s) for s in os.environ.get(
        "SURGE_BENCH_SOAK_SEEDS", "41,42,43,44").split(",") if s.strip()]
    seconds = float(os.environ.get("SURGE_BENCH_SOAK_SECONDS", 12.0))
    brokers = int(os.environ.get("SURGE_BENCH_SOAK_BROKERS", 3))
    partitions = int(os.environ.get("SURGE_BENCH_SOAK_PARTITIONS", 6))
    writers = int(os.environ.get("SURGE_BENCH_SOAK_WRITERS", 4))
    rounds = []
    for seed in seeds:
        log(f"soak bench: seed {seed} ({seconds:.0f}s schedule)")
        rounds.append(run_soak(seed, brokers=brokers, partitions=partitions,
                               seconds=seconds, writers=writers))
    verdict_ok = all(
        r["lost"] == 0 and r["duplicated"] == 0 and r["leaders"]["ok"]
        and r["converged"] and r["slo_pages"]["cleared"]
        and not r["writer_errors"] for r in rounds)
    return {
        "soak_rounds": rounds,
        "soak_seeds": seeds,
        "soak_acked_commits": sum(r["acked_commits"] for r in rounds),
        "soak_lost": sum(r["lost"] for r in rounds),
        "soak_duplicated": sum(r["duplicated"] for r in rounds),
        "soak_pages_raised": sum(r["slo_pages"]["raised"] for r in rounds),
        "soak_pages_cleared": all(r["slo_pages"]["cleared"] for r in rounds),
        "soak_balancer_moves": sum(r["balancer_moves"] for r in rounds),
        "soak_verdict": "ok: self-healed every schedule" if verdict_ok
        else "DEGRADED: see soak_rounds",
    }


def saga_bench() -> dict:
    """SURGE_BENCH_SAGA=1: the saga-storm chaos soak
    (surge_tpu.cluster.soak.run_saga_soak) — a storm of two-step transfer
    sagas (a seeded fraction poisoned into the compensation walk) against a
    3-broker spread cluster under a rolling broker kill, seeded link faults
    and a mid-storm SagaManager restart, per seed.

    Env: SURGE_BENCH_SAGA_SEEDS (comma list; default 61,62,63),
    SURGE_BENCH_SAGA_SECONDS (14 per seed), SURGE_BENCH_SAGA_COUNT (400
    sagas per seed), SURGE_BENCH_SAGA_BROKERS (3), SURGE_BENCH_SAGA_PARTITIONS
    (6), SURGE_BENCH_SAGA_ACCOUNTS (48), SURGE_BENCH_SAGA_POISON (0.3).

    The verdict aggregates every seed: **0 lost / 0 duplicated / 0
    half-compensated** — every acked saga terminal, every account balance
    equal to what the saga rows' own committed/compensated masks predict,
    and the ledger-reconciliation invariant clean over every row — with the
    whole story reconstructable from the merged flight timelines."""
    from surge_tpu.cluster.soak import run_saga_soak

    seeds = [int(s) for s in os.environ.get(
        "SURGE_BENCH_SAGA_SEEDS", "61,62,63").split(",") if s.strip()]
    seconds = float(os.environ.get("SURGE_BENCH_SAGA_SECONDS", 14.0))
    count = int(os.environ.get("SURGE_BENCH_SAGA_COUNT", 400))
    brokers = int(os.environ.get("SURGE_BENCH_SAGA_BROKERS", 3))
    partitions = int(os.environ.get("SURGE_BENCH_SAGA_PARTITIONS", 6))
    accounts = int(os.environ.get("SURGE_BENCH_SAGA_ACCOUNTS", 48))
    poison = float(os.environ.get("SURGE_BENCH_SAGA_POISON", 0.3))
    rounds = []
    for seed in seeds:
        log(f"saga storm: seed {seed} ({count} sagas, {seconds:.0f}s "
            "schedule)")
        rounds.append(run_saga_soak(
            seed, brokers=brokers, partitions=partitions, seconds=seconds,
            sagas=count, accounts=accounts, poison_fraction=poison))
    verdict_ok = all(
        r["lost"] == 0 and r["duplicated"] == 0
        and r["half_compensated"] == 0 and r["reconcile"]["ok"]
        for r in rounds)
    return {
        "saga_rounds": rounds,
        "saga_seeds": seeds,
        "saga_started": sum(r["started"] for r in rounds),
        "saga_poisoned": sum(r["poisoned"] for r in rounds),
        "saga_lost": sum(r["lost"] for r in rounds),
        "saga_duplicated": sum(r["duplicated"] for r in rounds),
        "saga_half_compensated": sum(r["half_compensated"] for r in rounds),
        "saga_dead_letter": sum(r["reconcile"]["dead_letter"]
                                for r in rounds),
        "saga_verdict": "ok: 0 lost / 0 duplicated / 0 half-compensated"
        if verdict_ok else "DEGRADED: see saga_rounds",
    }


def handoff_bench() -> dict:
    """SURGE_BENCH_HANDOFF=1: paired interleaved ladder (medians only, per
    the BENCH_NOTES round-6 protocol — single runs swing 2-3x on this host)
    comparing the three ways a partition leader moves:

    - ``handoff`` — planned HandoffPartition under load: bulk slice ship
      while serving, then fence -> journal-tail ship -> dedup push ->
      promote -> demote. Unavailability = the longest gap in the POOLED ack
      stream of all workers (the cluster-wide write outage, same metric as
      the failover bench — a single worker's private stall inside its retry
      ladder does not register); the fenced span is bounded by the TAIL
      appended during the bulk phase, never by log size.
    - ``kill`` — the PR-4 kill-failover under the same load: hard-kill the
      leader, prober-declared death, promotion. The unavailability floor
      includes the probe-failure detection window a planned handoff skips.
    - ``replay`` — full-replay cold start: how long an EMPTY standby takes
      to catch_up the whole preloaded log (the log-size-bound transfer a
      handoff performs UNFENCED). Runs with NO worker load — it measures
      pure transfer time against an idle leader, a different quantity than
      the two unavailability arms, compared only for its log-size scaling.

    Every round runs all three arms interleaved against fresh broker pairs
    with the same preload. Env: SURGE_BENCH_HANDOFF_WORKERS (8),
    SURGE_BENCH_HANDOFF_SECONDS (4), SURGE_BENCH_HANDOFF_PRELOAD (3000),
    SURGE_BENCH_HANDOFF_ROUNDS (3)."""
    import statistics
    import threading

    from surge_tpu.config import Config
    from surge_tpu.log import (GrpcLogTransport, InMemoryLog, LogRecord,
                               LogServer, TopicSpec)
    from surge_tpu.log.transport import NotLeaderError, ProducerFencedError

    workers = int(os.environ.get("SURGE_BENCH_HANDOFF_WORKERS", 8))
    seconds = float(os.environ.get("SURGE_BENCH_HANDOFF_SECONDS", 4.0))
    preload = int(os.environ.get("SURGE_BENCH_HANDOFF_PRELOAD", 3000))
    rounds = int(os.environ.get("SURGE_BENCH_HANDOFF_ROUNDS", 3))
    cfg = Config(overrides={
        "surge.log.replication-ack-timeout-ms": 1_500,
        "surge.log.replication-isr-timeout-ms": 2_000,
        "surge.log.failover.probe-interval-ms": 150,
        "surge.log.failover.probe-failures": 2,
    })

    def build_pair():
        lport, fport = _free_ports(2)
        follower = LogServer(InMemoryLog(), port=fport,
                             follower_of=f"127.0.0.1:{lport}",
                             auto_promote=True, config=cfg)
        follower.start()
        leader = LogServer(InMemoryLog(), port=lport,
                           replicate_to=[f"127.0.0.1:{fport}"], config=cfg)
        leader.start()
        setup = GrpcLogTransport(f"127.0.0.1:{lport}", config=cfg)
        setup.create_topic(TopicSpec("ev", 1))
        producer = setup.transactional_producer("preload")
        done = 0
        while done < preload:
            n = min(500, preload - done)
            producer.begin()
            for i in range(n):
                producer.send(LogRecord(topic="ev", key=f"p{done + i}",
                                        value=b"x" * 64, partition=0))
            producer.commit()
            done += n
        setup.close()
        return leader, follower, lport, fport

    def run_arm(kind: str) -> dict:
        leader, follower, lport, fport = build_pair()
        targets = f"127.0.0.1:{lport},127.0.0.1:{fport}"
        stop_at = time.monotonic() + seconds
        move_at = time.monotonic() + 0.4 * seconds
        acked_lock = threading.Lock()
        acked: list = []
        ack_times: list = []

        def worker(w: int) -> None:
            client = GrpcLogTransport(targets, config=cfg)
            producer = None
            i = 0
            try:
                while time.monotonic() < stop_at:
                    payload = f"{kind}-w{w}-{i}".encode()
                    deadline = time.monotonic() + 30.0
                    while True:
                        try:
                            if producer is None:
                                producer = client.transactional_producer(
                                    f"ho-{kind}-{w}")
                            producer.begin()
                            producer.send(LogRecord(
                                topic="ev", key=f"w{w}", value=payload,
                                partition=0))
                            producer.commit()
                            break
                        except (ProducerFencedError, NotLeaderError):
                            producer = None
                        except Exception:  # noqa: BLE001 — mid-transition
                            if producer is not None and producer.in_transaction:
                                producer.abort()
                            time.sleep(0.05)
                        if time.monotonic() > deadline:
                            return
                    with acked_lock:
                        acked.append(payload)
                        ack_times.append(time.monotonic())
                    i += 1
            finally:
                client.close()

        out: dict = {"kind": kind}
        threads = []
        if kind != "replay":
            threads = [threading.Thread(target=worker, args=(w,), daemon=True)
                       for w in range(workers)]
            for t in threads:
                t.start()
        moved = False
        admin = None
        try:
            while time.monotonic() < stop_at:
                if not moved and time.monotonic() >= move_at:
                    moved = True
                    if kind == "handoff":
                        admin = GrpcLogTransport(f"127.0.0.1:{lport}",
                                                 config=cfg)
                        out["handoff_stats"] = admin.handoff_partition(
                            f"127.0.0.1:{fport}")
                    elif kind == "kill":
                        leader.kill()
                    else:  # replay: full cold start of an EMPTY standby
                        (sport,) = _free_ports(1)
                        standby = LogServer(InMemoryLog(), port=sport,
                                            config=cfg)
                        t0 = time.perf_counter()
                        copied = standby.catch_up(f"127.0.0.1:{lport}")
                        out["replay_cold_start_ms"] = round(
                            (time.perf_counter() - t0) * 1000.0, 1)
                        out["replay_records"] = copied
                        standby.stop()
                        break
                time.sleep(0.02)
            for t in threads:
                t.join(60.0)
            if kind != "replay":
                deadline = time.monotonic() + 30
                winner = follower  # the destination/promoted broker
                while winner.role != "leader" and time.monotonic() < deadline:
                    time.sleep(0.02)
                gaps = [b - a for a, b in zip(ack_times, ack_times[1:])]
                out["unavailability_ms"] = (round(max(gaps) * 1000.0, 1)
                                            if gaps else None)
                out["acked"] = len(acked)
                present: dict = {}
                for r in winner.log.read("ev", 0):
                    present[r.value] = present.get(r.value, 0) + 1
                out["lost"] = sum(1 for p in acked
                                  if present.get(p, 0) == 0)
                out["duplicated"] = sum(1 for p in acked
                                        if present.get(p, 0) > 1)
                out["promoted"] = winner.role == "leader"
        finally:
            if admin is not None:
                admin.close()
            leader.stop()
            follower.stop()
        return out

    arms: dict = {"handoff": [], "kill": [], "replay": []}
    for rnd in range(rounds):
        for kind in ("handoff", "kill", "replay"):  # interleaved, paired
            try:
                row = run_arm(kind)
            except Exception as exc:  # noqa: BLE001 — one arm, not the ladder
                log(f"handoff bench round {rnd} {kind} FAILED: {exc!r}")
                row = {"kind": kind, "error": repr(exc)}
            row["round"] = rnd
            arms[kind].append(row)
            log(f"handoff bench round {rnd} {kind}: "
                f"{ {k: v for k, v in row.items() if k != 'handoff_stats'} }")
    med = lambda rows, k: statistics.median(  # noqa: E731
        r[k] for r in rows if r.get(k) is not None)
    out = {
        "workers": workers, "seconds": seconds, "preload": preload,
        "rounds": rounds, "arms": arms,
        "handoff_unavailability_ms_median": med(arms["handoff"],
                                                "unavailability_ms"),
        "kill_unavailability_ms_median": med(arms["kill"],
                                             "unavailability_ms"),
        "replay_cold_start_ms_median": med(arms["replay"],
                                           "replay_cold_start_ms"),
        "handoff_fence_ms_median": statistics.median(
            r["handoff_stats"]["fence_ms"] for r in arms["handoff"]
            if "handoff_stats" in r),
        "handoff_tail_records_median": statistics.median(
            r["handoff_stats"].get("tail_records", 0)
            for r in arms["handoff"] if "handoff_stats" in r),
        "lost": sum(r.get("lost", 0) for rows in arms.values()
                    for r in rows),
        "duplicated": sum(r.get("duplicated", 0) for rows in arms.values()
                          for r in rows),
    }
    log(f"handoff bench medians: planned {out['handoff_unavailability_ms_median']}ms "
        f"(fence {out['handoff_fence_ms_median']}ms, tail "
        f"{out['handoff_tail_records_median']} records) vs kill "
        f"{out['kill_unavailability_ms_median']}ms vs full-replay cold start "
        f"{out['replay_cold_start_ms_median']}ms over {preload} records; "
        f"lost={out['lost']} duplicated={out['duplicated']}")
    return out


def _free_ports(n: int) -> list:
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def producer_sweep(seconds: float) -> list:
    """Sweep the group-commit knobs at one fixed rung — the before/after
    evidence for the adaptive publisher. The ``linger_ms=50, max_in_flight=1,
    broker=inproc`` row approximates the retired fixed 50 ms flush tick with
    one serial transaction lane; the grpc rows exercise the pipelined
    Transact window against a loopback broker (in-process logs always run
    one commit in flight, so in-flight only moves the wire rows).

    Env: SURGE_BENCH_SWEEP_WORKERS (256), SURGE_BENCH_SWEEP_SECONDS
    (min(seconds, 3))."""
    workers = int(os.environ.get("SURGE_BENCH_SWEEP_WORKERS", 256))
    secs = float(os.environ.get("SURGE_BENCH_SWEEP_SECONDS",
                                min(seconds, 3.0)))
    combos = [
        (50, 1, "inproc"),  # the pre-group-commit fixed-tick envelope
        (5, 1, "inproc"),
        (2, 1, "inproc"),   # the shipped default
        (0, 1, "inproc"),
        (2, 1, "grpc"),     # pipelining off, over the wire
        (2, 4, "grpc"),     # the shipped default window, over the wire
        (2, 8, "grpc"),
    ]
    rows = []
    for linger, inflight, broker in combos:
        try:
            stats = steady_state_latency(secs, overrides={
                "surge.producer.linger-ms": linger,
                "surge.producer.max-in-flight": inflight,
                "bench.broker": broker,
            }, ladder=[workers])
        except Exception as exc:  # noqa: BLE001 — one combo must not void the sweep
            log(f"sweep combo linger={linger} in_flight={inflight} "
                f"broker={broker} failed: {exc!r}")
            rows.append({"linger_ms": linger, "max_in_flight": inflight,
                         "broker": broker,
                         "error": f"{type(exc).__name__}: {exc}"})
            continue
        rung = stats["throughput_ladder"][0]
        row = {"linger_ms": linger, "max_in_flight": inflight,
               "broker": broker, **rung}
        rows.append(row)
        log(f"sweep linger={linger}ms in_flight={inflight} broker={broker}: "
            f"{rung['commands_per_sec']} cmds/s p50 {rung['p50_ms']}ms "
            f"p99 {rung['p99_ms']}ms ({rung['commands_per_txn']} cmds/txn)")
    return rows


# --------------------------------------------------------------------------------------
# parent orchestration
# --------------------------------------------------------------------------------------

def _merge_replay(payload: dict, child: dict, cpu_eps: float) -> None:
    payload["value"] = child["events_per_sec"]
    payload["vs_baseline"] = round(child["events_per_sec"] / cpu_eps, 2) if cpu_eps else 0
    for k in ("platform", "aggregates_per_sec", "replay_s", "pad_ratio", "pack_s",
              "h2d_s", "windows", "compiles", "device_fold_events_per_sec",
              "upload_s", "densify_s", "fold_s", "steady_replay_s",
              "steady_events_per_sec", "wire_mb", "stream_segments", "knobs"):
        if k in child:
            payload[k] = child[k]
    # End-to-end cold-start accounting (VERDICT r4 missing #3), matching how
    # the reference's restore is judged — wall clock of the whole restore
    # (KafkaStreamsUpdatePartitionsOnStateChangeListener.scala:1-113):
    # - mmap hit (every restart after the first): mmap the packed wire +
    #   upload + fold = replay_s, so value/vs_baseline ARE end-to-end here
    # - first build (one-time): + the wire pack at segment-build time
    # corpus_build_s stays separate: it synthesizes the benchmark fixture the
    # reference reads out of its pre-existing Kafka topics.
    if "replay_s" in child:
        payload["cold_start_mmap_hit_s"] = child["replay_s"]
        first = round(payload.get("wire_pack_s", 0.0) + child["replay_s"], 2)
        payload["cold_start_first_build_s"] = first
        if cpu_eps and payload.get("num_events") and first > 0:
            payload["vs_baseline_first_build"] = round(
                payload["num_events"] / first / cpu_eps, 2)


def restore_bench() -> dict:
    """SURGE_BENCH_RESTORE=1: full vs checkpointed cold start (docs/compaction.md).

    Builds an events topic, checkpoints it at the head, appends a tail, then
    times ``restore_from_events`` from offset 0 against the checkpoint+tail
    route — reporting events folded and wall seconds for each, asserting the
    stores come out byte-identical and the checkpointed route folds strictly
    fewer events. Knobs: SURGE_BENCH_RESTORE_EVENTS (total, default 200k),
    SURGE_BENCH_RESTORE_TAIL (tail fraction, default 0.1),
    SURGE_BENCH_RESTORE_BACKEND (cpu|tpu, default the platform's replay
    backend: cpu here in the parent)."""
    import random
    import shutil
    import tempfile

    from surge_tpu.config import default_config
    from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
    from surge_tpu.models import counter
    from surge_tpu.serialization import SerializedMessage
    from surge_tpu.store import CheckpointStore, CheckpointWriter, restore_from_events
    from surge_tpu.store.kv import InMemoryKeyValueStore

    total = int(os.environ.get("SURGE_BENCH_RESTORE_EVENTS", 200_000))
    tail_frac = float(os.environ.get("SURGE_BENCH_RESTORE_TAIL", 0.1))
    backend = os.environ.get("SURGE_BENCH_RESTORE_BACKEND", "cpu")
    n_agg = max(total // 10, 1)
    model = counter.CounterModel()
    evt_fmt = counter.event_formatting()
    state_fmt = counter.state_formatting()
    deserialize_event = lambda b: evt_fmt.read_event(  # noqa: E731
        SerializedMessage(key="", value=b))
    serialize_state = lambda a, s: state_fmt.write_state(s).value  # noqa: E731

    log_t = InMemoryLog()
    log_t.create_topic(TopicSpec("events", 4))
    prod = log_t.transactional_producer("bench")
    rng = random.Random(11)
    seqs: dict = {}

    def publish(n: int) -> None:
        prod.begin()
        for i in range(n):
            a = f"agg-{rng.randrange(n_agg)}"
            seqs[a] = seqs.get(a, 0) + 1
            ev = (counter.CountIncremented(a, 1, seqs[a])
                  if rng.random() < 0.8
                  else counter.CountDecremented(a, 1, seqs[a]))
            prod.send(LogRecord(topic="events", key=a,
                                value=evt_fmt.write_event(ev).value,
                                partition=hash(a) % 4))
            if i % 5000 == 4999:
                prod.commit()
                prod.begin()
        prod.commit()

    head = total - int(total * tail_frac)
    publish(head)
    ck_dir = tempfile.mkdtemp(prefix="surge-bench-ckpt-")
    out: dict = {}
    try:
        writer = CheckpointWriter(
            log_t, "events", model, CheckpointStore(ck_dir, fsync=False),
            serialize_state=serialize_state,
            deserialize_event=deserialize_event,
            deserialize_state=state_fmt.read_state)
        t0 = time.perf_counter()
        ckpt = writer.write_now()
        out["restore_checkpoint_write_s"] = round(time.perf_counter() - t0, 3)
        publish(total - head)

        cfg = default_config().with_overrides({
            "surge.replay.backend": backend,
            "surge.replay.restore-spill-events": -1})
        full_store, ckpt_store = InMemoryKeyValueStore(), InMemoryKeyValueStore()
        t0 = time.perf_counter()
        full = restore_from_events(
            log_t, "events", full_store, deserialize_event=deserialize_event,
            serialize_state=serialize_state, model=model,
            replay_spec=counter.make_replay_spec(), config=cfg)
        full_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        tail = restore_from_events(
            log_t, "events", ckpt_store, deserialize_event=deserialize_event,
            serialize_state=serialize_state, model=model,
            replay_spec=counter.make_replay_spec(), config=cfg,
            checkpoint=ckpt, deserialize_state=state_fmt.read_state)
        ckpt_s = time.perf_counter() - t0
        mismatch = sum(
            1 for k in set(full_store._data) | set(ckpt_store._data)
            if full_store.get(k) != ckpt_store.get(k))
        if mismatch or tail.num_events >= full.num_events:
            raise AssertionError(
                f"checkpointed restore invariant broken: {mismatch} mismatched "
                f"aggregates, {tail.num_events} vs {full.num_events} events")
        out.update({
            "restore_backend": backend,
            "restore_full_events_folded": full.num_events,
            "restore_full_s": round(full_s, 3),
            "restore_ckpt_events_folded": tail.num_events,
            "restore_ckpt_s": round(ckpt_s, 3),
            "restore_speedup": round(full_s / ckpt_s, 2) if ckpt_s else 0.0,
        })
        log(f"restore bench ({backend}): full {full.num_events} events "
            f"{full_s:.2f}s vs checkpointed {tail.num_events} events "
            f"{ckpt_s:.2f}s ({out['restore_speedup']}x, byte-identical)")
        return out
    finally:
        shutil.rmtree(ck_dir, ignore_errors=True)


def resident_bench() -> dict:
    """SURGE_BENCH_RESIDENT=1 fast path: the device-resident state plane
    (docs/replay.md "Resident state plane").

    Three measurements, each PAIRED + INTERLEAVED per the BENCH_NOTES.md
    round-6 protocol (this host's single runs swing 2-3x; only same-round
    pairs and cross-round medians count):

    1. **Read ladder** — k concurrent readers issuing read-side projections
       (batches of SURGE_BENCH_RESIDENT_BATCH aggregates, the read-heavy
       workload the plane exists for): the batched-gather lane (every
       concurrent call coalesces into one device gather + a single
       fetch-barriered pull + a batch-materialized decode) vs the host KV
       path (per-key store bytes + state deserialize, exactly the engine's
       fallback — measured sync, its best case). Medians over >=3
       interleaved rounds per rung; a secondary single-read row records the
       per-getState surface, whose per-call asyncio cost the host path does
       not pay.
    2. **Refresh-loop sustained folds** — committed batches appended while
       the standing refresh loop folds them into the slab; events/s over the
       whole append->caught-up window.
    3. **Command-path guard** — one BENCH_LADDER-style rung with the plane
       enabled vs disabled, interleaved: the refresh loop must not regress
       the write path it shares the event loop with.

    Knobs: SURGE_BENCH_RESIDENT_AGGREGATES (4096), _EVENTS_PER (8),
    _ROUNDS (3), _BATCH (projection size, 256), _LOOPS (projections per
    worker per rung, 2), _READS (single reads per worker, 30), _LADDER
    ("16,64,256,1024"), _FOLD_EVENTS (60000), _GUARD (1; 0 skips phase 3),
    _GUARD_SECONDS (3.0), _GUARD_WORKERS (64)."""
    import asyncio
    import statistics

    from surge_tpu.config import default_config
    from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
    from surge_tpu.models import counter
    from surge_tpu.replay.ledger import ReplayLedger
    from surge_tpu.replay.profiler import ReplayProfiler
    from surge_tpu.replay.resident_state import ResidentStatePlane
    from surge_tpu.serialization import SerializedMessage
    from surge_tpu.store.kv import InMemoryKeyValueStore
    from surge_tpu.store.restore import restore_from_events

    n_agg = int(os.environ.get("SURGE_BENCH_RESIDENT_AGGREGATES", 4096))
    events_per = int(os.environ.get("SURGE_BENCH_RESIDENT_EVENTS_PER", 8))
    rounds = max(int(os.environ.get("SURGE_BENCH_RESIDENT_ROUNDS", 3)), 1)
    batch = int(os.environ.get("SURGE_BENCH_RESIDENT_BATCH", 256))
    loops = int(os.environ.get("SURGE_BENCH_RESIDENT_LOOPS", 2))
    reads_per_worker = int(os.environ.get("SURGE_BENCH_RESIDENT_READS", 30))
    ladder = [int(w) for w in os.environ.get(
        "SURGE_BENCH_RESIDENT_LADDER", "16,64,256,1024").split(",") if w]
    fold_events = int(os.environ.get("SURGE_BENCH_RESIDENT_FOLD_EVENTS", 60_000))

    evt_fmt = counter.event_formatting()
    state_fmt = counter.state_formatting()
    npart = 4
    aggs = [f"agg-{i}" for i in range(n_agg)]
    seqs = {a: 0 for a in aggs}

    log_t = InMemoryLog()
    log_t.create_topic(TopicSpec("events", npart))
    prod = log_t.transactional_producer("bench")

    def publish(agg_events) -> None:
        prod.begin()
        for i, (a, ev) in enumerate(agg_events):
            prod.send(LogRecord(topic="events", key=a,
                                value=evt_fmt.write_event(ev).value,
                                partition=hash(a) % npart))
            if i % 5000 == 4999:
                prod.commit()
                prod.begin()
        prod.commit()

    def make_batch(n: int):
        batch = []
        for i in range(n):
            a = aggs[(i * 7919) % n_agg]
            seqs[a] += 1
            batch.append((a, counter.CountIncremented(a, 1, seqs[a])))
        return batch

    publish(make_batch(n_agg * events_per))

    # the host read path the engine falls back to: indexed KV bytes + the
    # state deserialize chain
    host_store = InMemoryKeyValueStore()
    restore_from_events(
        log_t, "events", host_store,
        deserialize_event=lambda b: evt_fmt.read_event(
            SerializedMessage(key="", value=b)),
        serialize_state=lambda a, s: state_fmt.write_state(s).value,
        model=counter.CounterModel(), replay_spec=counter.make_replay_spec(),
        config=default_config().with_overrides(
            {"surge.replay.backend": "cpu"}))

    def host_read(agg: str):
        return state_fmt.read_state(host_store.get(agg))

    out: dict = {"resident_aggregates": n_agg,
                 "resident_seed_events": n_agg * events_per,
                 "resident_rounds": rounds}

    async def scenario() -> None:
        # the device observatory rides the measured plane (the production
        # default since ISSUE 16): the ledger's per-round accounting is what
        # the waste-ratio / per-stage rows below read, and the overhead arm
        # detaches it to prove the riding costs nothing
        ledger = ReplayLedger(name="bench:resident")
        observatory = ReplayProfiler.counters()
        plane = ResidentStatePlane(
            log_t, "events", counter.make_replay_spec(),
            config=default_config().with_overrides({
                "surge.replay.resident.capacity": max(n_agg, 8),
                "surge.replay.resident.refresh-interval-ms": 10,
            }),
            deserialize_event=lambda b: evt_fmt.read_event(
                SerializedMessage(key="", value=b)),
            serialize_state=lambda a, s: state_fmt.write_state(s).value,
            profiler=observatory, ledger=ledger)
        t0 = time.perf_counter()
        await plane.start()
        out["resident_seed_s"] = round(time.perf_counter() - t0, 2)
        log(f"resident plane seeded: {plane.occupancy()} aggregates in "
            f"{out['resident_seed_s']}s")

        def ids_for(w: int, j: int):
            return [aggs[(w * batch + j * 137 + x) % n_agg]
                    for x in range(batch)]

        async def dev_worker(w: int) -> None:
            for j in range(loops):
                got = await plane.read_many(ids_for(w, j))
                if len(got) != batch:
                    raise RuntimeError("resident projection missed")

        async def host_worker(w: int) -> None:
            for j in range(loops):
                for a in ids_for(w, j):
                    if host_read(a) is None:
                        raise RuntimeError("host read missed")

        async def dev_single(w: int) -> None:
            for j in range(reads_per_worker):
                hit, st = await plane.read_state(aggs[(w * 9176 + j * 31) % n_agg])
                if not hit or st is None:
                    raise RuntimeError("resident read missed")

        async def rung(workers: int, fn, per_worker: int) -> float:
            t0 = time.perf_counter()
            await asyncio.gather(*(fn(w) for w in range(workers)))
            return workers * per_worker / (time.perf_counter() - t0)

        # warmup: compile every rung's padded gather bucket outside the
        # measured rounds (jit caches per shape)
        for w in ladder:
            await rung(w, dev_worker, loops * batch)
        await rung(max(ladder), dev_single, reads_per_worker)

        per_rung: dict = {w: {"device": [], "host": []} for w in ladder}
        singles = []
        for rnd in range(rounds):
            for w in ladder:
                # alternate intra-round order so neither side always runs
                # into the other's cache/GC wake
                order = (("host", host_worker), ("device", dev_worker))
                if rnd % 2:
                    order = order[::-1]
                for name, fn in order:
                    per_rung[w][name].append(
                        await rung(w, fn, loops * batch))
            singles.append(await rung(max(ladder), dev_single,
                                      reads_per_worker))
        gathers0, rows0 = plane.stats["gathers"], plane.stats["gathered_rows"]
        out["resident_read_batch"] = batch
        out["resident_read_ladder"] = [{
            "workers": w,
            "device_reads_per_sec": round(statistics.median(per_rung[w]["device"])),
            "host_reads_per_sec": round(statistics.median(per_rung[w]["host"])),
            "device_vs_host": round(statistics.median(per_rung[w]["device"])
                                    / statistics.median(per_rung[w]["host"]), 2),
            "device_rounds": [round(x) for x in per_rung[w]["device"]],
            "host_rounds": [round(x) for x in per_rung[w]["host"]],
        } for w in ladder]
        out["resident_single_reads_per_sec"] = round(statistics.median(singles))
        out["resident_gather_rows_per_gather"] = round(rows0 / max(gathers0, 1), 1)
        for r in out["resident_read_ladder"]:
            log(f"read ladder @{r['workers']}x{batch}: device "
                f"{r['device_reads_per_sec']} vs host "
                f"{r['host_reads_per_sec']} reads/s ({r['device_vs_host']}x)")
        log(f"single-read surface @{max(ladder)}: "
            f"{out['resident_single_reads_per_sec']} reads/s")

        # -- sustained incremental folds through the standing refresh loop --
        folded0 = plane.stats["folded_events"]
        t0 = time.perf_counter()
        publish(make_batch(fold_events))
        while plane.lag_records() > 0:
            await asyncio.sleep(0.01)
        fold_s = time.perf_counter() - t0
        folded = plane.stats["folded_events"] - folded0
        out["resident_fold_events"] = folded
        out["resident_fold_s"] = round(fold_s, 2)
        out["resident_fold_events_per_sec"] = round(folded / fold_s)
        out["resident_fold_rounds"] = plane.stats["rounds"]
        log(f"refresh loop: {folded} events folded in {fold_s:.2f}s "
            f"({out['resident_fold_events_per_sec']} ev/s sustained)")

        # -- the device observatory's read of the same fold window --------
        summ = ledger.summary()
        stages = ledger.round_stages_us()
        med_us = lambda k: (round(statistics.median(stages[k]))  # noqa: E731
                            if stages[k] else 0)
        out["resident_waste_ratio"] = round(summ["waste_ratio"], 2)
        out["resident_us_per_slot"] = round(summ["us_per_slot"], 2)
        out["resident_stage_medians_us"] = {
            "feed": med_us("feed_us"), "encode": med_us("encode_us"),
            "dispatch": med_us("dispatch_us")}
        s = out["resident_stage_medians_us"]
        log(f"observatory: waste {out['resident_waste_ratio']}x, "
            f"{out['resident_us_per_slot']} us/slot, round medians "
            f"feed {s['feed']} / encode {s['encode']} / "
            f"dispatch {s['dispatch']} us")

        # -- observatory overhead: ledger+profiler on vs OFF, interleaved --
        # (the always-on claim: counters are perf_counter pairs + dict adds;
        # the paired medians must sit inside this host's noise band)
        obs_cycles = int(os.environ.get("SURGE_BENCH_RESIDENT_OBS_CYCLES", 4))
        obs_events = int(os.environ.get(
            "SURGE_BENCH_RESIDENT_OBS_EVENTS", 10_000))
        if obs_cycles:
            obs: dict = {"on": [], "off": []}
            for rnd in range(rounds):
                order = ("off", "on") if rnd % 2 else ("on", "off")
                for name in order:
                    plane.ledger = ledger if name == "on" else None
                    plane.profiler = observatory if name == "on" else None
                    t0 = time.perf_counter()
                    for _ in range(obs_cycles):
                        publish(make_batch(obs_events))
                        while plane.lag_records() > 0:
                            await asyncio.sleep(0.01)
                    obs[name].append(obs_cycles * obs_events
                                     / (time.perf_counter() - t0))
            out["resident_observatory_overhead"] = {
                "events_per_cycle": obs_events, "cycles": obs_cycles,
                "on_events_per_sec": round(statistics.median(obs["on"])),
                "off_events_per_sec": round(statistics.median(obs["off"])),
                "on_vs_off": round(statistics.median(obs["on"])
                                   / statistics.median(obs["off"]), 3),
                "on_rounds": [round(x) for x in obs["on"]],
                "off_rounds": [round(x) for x in obs["off"]],
            }
            o = out["resident_observatory_overhead"]
            log(f"observatory overhead: on {o['on_events_per_sec']} vs off "
                f"{o['off_events_per_sec']} ev/s ({o['on_vs_off']}x, "
                f"medians over {rounds} interleaved rounds)")
        await plane.stop()

    asyncio.run(scenario())

    # -- command-path guard: the refresh loop must not cost the write path --
    if os.environ.get("SURGE_BENCH_RESIDENT_GUARD", "1") == "1":
        secs = float(os.environ.get("SURGE_BENCH_RESIDENT_GUARD_SECONDS", 3.0))
        workers = int(os.environ.get("SURGE_BENCH_RESIDENT_GUARD_WORKERS", 64))
        guard: dict = {"off": [], "on": []}
        for rnd in range(rounds):
            order = (("off", False), ("on", True))
            if rnd % 2:
                order = order[::-1]
            for name, enabled in order:
                stats = steady_state_latency(secs, overrides={
                    "surge.replay.resident.enabled": enabled,
                }, ladder=[workers])
                guard[name].append({"commands_per_sec": stats["commands_per_sec"],
                                    "p50_ms": stats["command_p50_ms"]})
        med = lambda rows, k: statistics.median(r[k] for r in rows)  # noqa: E731
        out["resident_command_guard"] = {
            "workers": workers, "seconds": secs, "rounds": guard,
            "plane_off_commands_per_sec": round(med(guard["off"], "commands_per_sec")),
            "plane_on_commands_per_sec": round(med(guard["on"], "commands_per_sec")),
            "plane_off_p50_ms": round(med(guard["off"], "p50_ms"), 2),
            "plane_on_p50_ms": round(med(guard["on"], "p50_ms"), 2),
        }
        g = out["resident_command_guard"]
        log(f"command guard @{workers}w: plane on "
            f"{g['plane_on_commands_per_sec']} vs off "
            f"{g['plane_off_commands_per_sec']} cmds/s (medians, "
            f"p50 {g['plane_on_p50_ms']} vs {g['plane_off_p50_ms']} ms)")
    return out


def mesh_bench() -> dict:
    """SURGE_BENCH_MESH=1: the mesh-native resident plane + sharded scans on
    a forced 8-device host mesh (the tier-1 topology; on silicon the same
    arms run over real chips).

    Three measurements, each PAIRED + INTERLEAVED per the BENCH_NOTES round-6
    protocol (single runs on this host swing 2-3×; only same-round pairs and
    cross-round medians count):

    1. **Capacity fold ladder** — steady-state incremental refresh throughput
       (events/s across publish→caught-up cycles) per rung, where the RUNG IS
       THE SLAB CAPACITY, arms = ``surge.replay.mesh.gather`` local vs
       replicated. When the refresh scatter is undonated
       (``surge.replay.donate-refresh`` off — the regime BENCH_MESH_r01 was
       measured in; donation is on by default since ISSUE 18) every window
       copies the slab it writes: the replicated arm copies the FULL slab on
       every replica while the local arm copies one 1/n_dev shard each — the
       cost that scales with the resident set. The local arm holds flat up the
       ladder; the replicated arm collapses (that cliff is WHY multi-device
       is the first-class path for millions of resident aggregates).
    2. **Read row** — batched ``read_many`` projections per arm: device-local
       gathers + ONE collective vs gathers against the replicated slab. On
       forced host devices (shared memory, 2 vCPUs) this row sits near parity
       — the collective costs and the locality wins cancel; on silicon the
       replicated arm additionally pays n_dev× HBM for the slab.
    3. **Sharded-scan row** — QueryEngine grouped-aggregate scan events/s,
       mesh-sharded vs single-device, over the same columnar chunks.

    Knobs: SURGE_BENCH_MESH_AGGREGATES (512), _ROUNDS (3), _CAP_LADDER
    ("262144,1048576"), _FOLD_EVENTS (512 per cycle), _FOLD_CYCLES (16),
    _READ_WORKERS (16), _READ_BATCH (256), _READ_LOOPS (2),
    _SCAN_EVENTS (200000)."""
    import asyncio
    import statistics

    import jax

    from surge_tpu.codec.tensor import encode_events_columnar
    from surge_tpu.config import default_config
    from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
    from surge_tpu.models import counter
    from surge_tpu.replay.query import Aggregate, Predicate, QueryEngine, ScanQuery
    from surge_tpu.replay.resident_state import ResidentStatePlane
    from surge_tpu.serialization import SerializedMessage

    devs = jax.devices()
    assert len(devs) >= 8, (
        f"mesh bench needs 8 forced host devices, got {len(devs)} — main() "
        "must set xla_force_host_platform_device_count before jax init")
    mesh = jax.sharding.Mesh(np.array(devs[:8]), ("data",))

    n_agg = int(os.environ.get("SURGE_BENCH_MESH_AGGREGATES", 512))
    rounds = max(int(os.environ.get("SURGE_BENCH_MESH_ROUNDS", 3)), 1)
    cap_ladder = [int(x) for x in os.environ.get(
        "SURGE_BENCH_MESH_CAP_LADDER", "262144,1048576").split(",") if x]
    fold_events = int(os.environ.get("SURGE_BENCH_MESH_FOLD_EVENTS", 512))
    fold_cycles = int(os.environ.get("SURGE_BENCH_MESH_FOLD_CYCLES", 16))
    read_workers = int(os.environ.get("SURGE_BENCH_MESH_READ_WORKERS", 16))
    read_batch = int(os.environ.get("SURGE_BENCH_MESH_READ_BATCH", 256))
    read_loops = int(os.environ.get("SURGE_BENCH_MESH_READ_LOOPS", 2))
    scan_events = int(os.environ.get("SURGE_BENCH_MESH_SCAN_EVENTS", 200_000))

    evt_fmt = counter.event_formatting()
    state_fmt = counter.state_formatting()
    npart = 4
    aggs = [f"agg-{i}" for i in range(n_agg)]
    out: dict = {"mesh_devices": 8, "mesh_aggregates": n_agg,
                 "mesh_rounds": rounds}

    def make_plane_log():
        seqs = {a: 0 for a in aggs}
        log_t = InMemoryLog()
        log_t.create_topic(TopicSpec("events", npart))
        prod = log_t.transactional_producer("bench")

        def publish(n: int) -> None:
            prod.begin()
            for i in range(n):
                a = aggs[(i * 7919) % n_agg]
                seqs[a] += 1
                ev = counter.CountIncremented(a, 1, seqs[a])
                prod.send(LogRecord(topic="events", key=a,
                                    value=evt_fmt.write_event(ev).value,
                                    partition=hash(a) % npart))
                if i % 5000 == 4999:
                    prod.commit()
                    prod.begin()
            prod.commit()

        publish(n_agg * 4)  # the seed corpus
        return log_t, publish

    async def plane_arm(gather: str, cap: int, log_t, publish,
                        measure_reads: bool):
        """One arm at one capacity rung: steady-state fold cycles (+ the
        read row at the first rung). Returns (fold eps, reads/s|None,
        the arm's device-observatory ledger summary + stage columns)."""
        from surge_tpu.replay.ledger import ReplayLedger

        ledger = ReplayLedger(name=f"bench:mesh:{gather}")
        plane = ResidentStatePlane(
            log_t, "events", counter.make_replay_spec(),
            config=default_config().with_overrides({
                "surge.replay.resident.capacity": cap,
                "surge.replay.resident.refresh-interval-ms": 1,
                "surge.replay.mesh.gather": gather,
            }),
            deserialize_event=lambda b: evt_fmt.read_event(
                SerializedMessage(key="", value=b)),
            serialize_state=lambda a, s: state_fmt.write_state(s).value,
            mesh=mesh, ledger=ledger)
        await plane.start()
        try:
            publish(fold_events)  # warm the refresh program's shape bucket
            while plane.lag_records() > 0:
                await asyncio.sleep(0.002)
            t0 = time.perf_counter()
            for _ in range(fold_cycles):
                publish(fold_events)
                while plane.lag_records() > 0:
                    await asyncio.sleep(0.002)
            eps = fold_cycles * fold_events / (time.perf_counter() - t0)
            reads = None
            if measure_reads:
                async def reader(w: int) -> None:
                    for j in range(read_loops):
                        ids = [aggs[(w * read_batch + j * 137 + x) % n_agg]
                               for x in range(read_batch)]
                        got = await plane.read_many(ids)
                        if len(got) != read_batch:
                            raise RuntimeError("mesh projection missed")

                await reader(0)  # warm the gather bucket
                t0 = time.perf_counter()
                await asyncio.gather(*(reader(w)
                                       for w in range(read_workers)))
                reads = (read_workers * read_loops * read_batch
                         / (time.perf_counter() - t0))
            summ = ledger.summary()
            stages = ledger.round_stages_us()
            obs = {"waste_ratio": summ["waste_ratio"],
                   "us_per_slot": summ["us_per_slot"],
                   "stages": stages}
            return eps, reads, obs
        finally:
            await plane.stop()

    per_rung: dict = {c: {"local": [], "replicated": []} for c in cap_ladder}
    read_rows: dict = {"local": [], "replicated": []}
    obs_rows: dict = {"local": [], "replicated": []}
    for rnd in range(rounds):
        order = ("replicated", "local") if rnd % 2 else ("local", "replicated")
        for cap in cap_ladder:
            for arm in order:
                log_t, publish = make_plane_log()  # identical fresh log/arm
                eps, reads, obs = asyncio.run(plane_arm(
                    arm, cap, log_t, publish,
                    measure_reads=cap == cap_ladder[0]))
                per_rung[cap][arm].append(eps)
                if reads is not None:
                    read_rows[arm].append(reads)
                if cap == cap_ladder[0]:
                    obs_rows[arm].append(obs)
    med = statistics.median
    out["mesh_fold_ladder"] = [{
        "capacity": c,
        "events_per_cycle": fold_events,
        "local_events_per_sec": round(med(per_rung[c]["local"])),
        "replicated_events_per_sec": round(med(per_rung[c]["replicated"])),
        "local_vs_replicated": round(med(per_rung[c]["local"])
                                     / med(per_rung[c]["replicated"]), 2),
        "local_rounds": [round(x) for x in per_rung[c]["local"]],
        "replicated_rounds": [round(x) for x in per_rung[c]["replicated"]],
    } for c in cap_ladder]
    out["mesh_read_row"] = {
        "workers": read_workers, "batch": read_batch,
        "local_reads_per_sec": round(med(read_rows["local"])),
        "replicated_reads_per_sec": round(med(read_rows["replicated"])),
        "local_vs_replicated": round(med(read_rows["local"])
                                     / med(read_rows["replicated"]), 2),
    }
    for r in out["mesh_fold_ladder"]:
        log(f"capacity ladder @{r['capacity']}: local "
            f"{r['local_events_per_sec']} vs replicated "
            f"{r['replicated_events_per_sec']} ev/s "
            f"({r['local_vs_replicated']}x)")
    rr = out["mesh_read_row"]
    log(f"read row @{read_workers}x{read_batch}: local "
        f"{rr['local_reads_per_sec']} vs replicated "
        f"{rr['replicated_reads_per_sec']} reads/s "
        f"({rr['local_vs_replicated']}x)")

    # -- the device observatory's read of the first rung, per arm ----------
    out["mesh_observatory"] = {}
    for arm in ("local", "replicated"):
        waste = med(o["waste_ratio"] for o in obs_rows[arm])
        all_stages = {k: [v for o in obs_rows[arm]
                          for v in o["stages"][k]]
                      for k in ("feed_us", "encode_us", "dispatch_us")}
        out["mesh_observatory"][arm] = {
            "waste_ratio": round(waste, 2),
            "us_per_slot": round(med(o["us_per_slot"]
                                     for o in obs_rows[arm]), 2),
            "stage_medians_us": {
                k[:-3]: (round(med(v)) if v else 0)
                for k, v in all_stages.items()},
        }
        o = out["mesh_observatory"][arm]
        s = o["stage_medians_us"]
        log(f"observatory [{arm}]: waste {o['waste_ratio']}x, "
            f"{o['us_per_slot']} us/slot, round medians feed {s['feed']} / "
            f"encode {s['encode']} / dispatch {s['dispatch']} us")

    # -- sharded-scan throughput row (the query engine) ---------------------
    import random as _random

    rng = _random.Random(23)
    spec = counter.make_replay_spec()
    per_agg = max(scan_events // n_agg, 1)
    logs = []
    for i in range(n_agg):
        logs.append([counter.CountIncremented(str(i), rng.randrange(1, 4),
                                              k + 1)
                     for k in range(per_agg)])
    colev = encode_events_columnar(spec.registry, logs)
    colev.aggregate_ids = [str(i) for i in range(n_agg)]
    q = ScanQuery(aggregates=(Aggregate("count"),
                              Aggregate("sum", "increment_by"),
                              Aggregate("max", "sequence_number")),
                  predicates=(Predicate("increment_by", ">=", 2),))
    scans: dict = {"mesh": [], "single": []}
    engines = {"mesh": QueryEngine(spec, mesh=mesh),
               "single": QueryEngine(spec)}
    for arm, eng in engines.items():
        eng.scan_chunks([colev], q)  # warm/compile outside the timed rounds
    for rnd in range(rounds):
        order = ("single", "mesh") if rnd % 2 else ("mesh", "single")
        for arm in order:
            t0 = time.perf_counter()
            res = engines[arm].scan_chunks([colev], q)
            scans[arm].append(res.scanned_events
                              / (time.perf_counter() - t0))
    out["mesh_scan_row"] = {
        "events": colev.num_events,
        "mesh_events_per_sec": round(med(scans["mesh"])),
        "single_events_per_sec": round(med(scans["single"])),
        "mesh_vs_single": round(med(scans["mesh"]) / med(scans["single"]), 2),
    }
    sr = out["mesh_scan_row"]
    log(f"scan row @{sr['events']}ev: mesh {sr['mesh_events_per_sec']} vs "
        f"single {sr['single_events_per_sec']} ev/s "
        f"({sr['mesh_vs_single']}x)")
    return out


def ragged_bench() -> dict:
    """SURGE_BENCH_RAGGED=1: the bucketed ragged refresh dispatch (ISSUE 18),
    PAIRED + INTERLEAVED per the round-6 protocol — arms alternate within
    every round and only cross-round medians count.

    Two measurements:

    1. **Refresh ladder** — sustained incremental refresh throughput per
       shape × arm: per cycle the batch is published (untimed — the
       transactional publish is identical across arms), then the refresh
       DRAIN is timed over manual ``_refresh_once`` rounds; each arm-round's
       figure is the MEDIAN of its per-cycle drain rates (one 2-vCPU
       scheduler spike must not decide a round). Shapes: the
       device-observatory steady-ragged round (~10 lanes, short ragged
       tails — the ~9-10x over-dispatch regime BENCH_NOTES round 9 named)
       trickling into a PRODUCTION-sized 64Ki-row resident set, and the
       uniform dense 512-lane round. Arms: **dense** is the pre-PR refresh
       of record (the single ``[pow8(lanes), window]`` rectangle per
       window AND the copying scatter — ``donate-refresh`` off),
       **bucketed** the new defaults (one fused program per occupied pow2
       length bucket, donated scatter), **bucketed_pallas** bucketed plans
       folding through the ragged Pallas tile — interpreter mode on this
       CPU host, a correctness arm whose wall numbers only mean something
       on silicon. Waste ratios, µs/slot and per-stage medians read off
       each arm's ReplayLedger (the PR-16 pattern: the payload and
       ``DumpReplayLedger`` cannot disagree).
    2. **Donation probe** — the 1M-row mesh-local refresh device leg,
       donate-refresh on vs off (paired, interleaved): round-10 measured
       19 ms/window (local) vs 49 ms (replicated) at this rung and named
       the undonated slab copy as the cost; the donated arm must beat the
       copying arm on the same host.

    Knobs: SURGE_BENCH_RAGGED_ROUNDS (3), _CYCLES (24 publish→drain
    cycles per arm), _DENSE_LANES (512), _CAPACITY (65536 — the steady
    shape's resident set), _PROBE_CAPACITY (1048576), _PROBE_CYCLES (4),
    _PROBE (1 — 0 skips the mesh probe)."""
    import asyncio
    import random
    import statistics

    import jax

    from surge_tpu.config import default_config
    from surge_tpu.log import InMemoryLog, LogRecord, TopicSpec
    from surge_tpu.models import counter
    from surge_tpu.replay.ledger import ReplayLedger
    from surge_tpu.replay.resident_state import ResidentStatePlane
    from surge_tpu.serialization import SerializedMessage

    rounds = max(int(os.environ.get("SURGE_BENCH_RAGGED_ROUNDS", 3)), 1)
    cycles = int(os.environ.get("SURGE_BENCH_RAGGED_CYCLES", 24))
    dense_lanes = int(os.environ.get("SURGE_BENCH_RAGGED_DENSE_LANES", 512))
    steady_cap = int(os.environ.get("SURGE_BENCH_RAGGED_CAPACITY", 65536))
    probe_cap = int(os.environ.get(
        "SURGE_BENCH_RAGGED_PROBE_CAPACITY", 1_048_576))
    probe_cycles = int(os.environ.get("SURGE_BENCH_RAGGED_PROBE_CYCLES", 4))
    run_probe = os.environ.get("SURGE_BENCH_RAGGED_PROBE", "1") == "1"

    evt_fmt = counter.event_formatting()
    state_fmt = counter.state_formatting()
    npart = 4
    med = statistics.median

    # the dense arm is the PRE-PR refresh of record — the single padded
    # rectangle per window AND the copying (undonated) scatter, exactly what
    # shipped before ISSUE 18; bucketed/bucketed_pallas ride the new
    # defaults (bucketed dispatch + donated scatter). The decompositions
    # stay isolated: waste_ratio measures bucketing alone, the 1M-row probe
    # measures donation alone (both its arms bucketed).
    ARMS = {
        "dense": {"surge.replay.resident.refresh-dispatch": "dense",
                  "surge.replay.donate-refresh": False},
        "bucketed": {"surge.replay.resident.refresh-dispatch": "bucketed"},
        "bucketed_pallas": {
            "surge.replay.resident.refresh-dispatch": "bucketed",
            "surge.replay.tile-backend": "pallas",
            "surge.replay.dispatch": "select"},
    }
    # (lanes, tails(rng) -> per-lane event count) — every arm of a round
    # replays the IDENTICAL per-cycle workload (same seed, fresh log). The
    # steady-ragged shape is the observatory's (~10 lanes, short tails):
    # tails 5-8 land in ONE pow2 width bucket, so the bucketed arm's win is
    # pure lane-padding shed ([16,8] vs the dense [64,8] rectangle) — rounds
    # whose tails straddle several width buckets additionally pay one
    # program call per bucket, which on this 2-vCPU host is the dominant
    # cost at 10-lane sizes (see BENCH_NOTES round 11's honest-read)
    # the steady-ragged shape runs against a PRODUCTION-sized resident set
    # (_CAPACITY rows, not the observatory test's 64): trickling ragged
    # updates into a big slab is the round-9/10 roofline regime, and the
    # capacity is what the pre-PR copying scatter pays per window
    SHAPES = {
        "steady_ragged": (10, lambda rng: rng.randrange(5, 9), steady_cap),
        f"dense_{dense_lanes}": (dense_lanes, lambda rng: 4, dense_lanes),
    }

    def make_arm_log(n_lanes):
        log_t = InMemoryLog()
        log_t.create_topic(TopicSpec("events", npart))
        prod = log_t.transactional_producer("bench")
        seqs = {f"agg-{i}": 0 for i in range(n_lanes)}

        def publish(batch):
            prod.begin()
            for a, n in batch:
                for _ in range(n):
                    seqs[a] += 1
                    ev = counter.CountIncremented(a, 1, seqs[a])
                    prod.send(LogRecord(topic="events", key=a,
                                        value=evt_fmt.write_event(ev).value,
                                        partition=hash(a) % npart))
            prod.commit()
        return log_t, publish

    def make_plane(log_t, cap, ledger, overrides, mesh=None):
        return ResidentStatePlane(
            log_t, "events", counter.make_replay_spec(),
            config=default_config().with_overrides({
                "surge.replay.resident.capacity": cap,
                "surge.replay.resident.refresh-interval-ms": 1,
                "surge.replay.time-chunk": 8,
                **overrides,
            }),
            deserialize_event=lambda b: evt_fmt.read_event(
                SerializedMessage(key="", value=b)),
            serialize_state=lambda a, s: state_fmt.write_state(s).value,
            mesh=mesh, ledger=ledger)

    async def refresh_arm(arm, shape, seed):
        n_lanes, tails, cap = SHAPES[shape]
        rng = random.Random(seed)
        batches = [[(f"agg-{i}", tails(rng)) for i in range(n_lanes)]
                   for _ in range(cycles + 1)]
        log_t, publish = make_arm_log(n_lanes)
        ledger = ReplayLedger(name=f"bench:ragged:{arm}")
        plane = make_plane(log_t, cap, ledger, ARMS[arm])
        plane._ensure_device_state()
        plane.seed_from_log()
        try:
            publish(batches[0])  # warm the arm's program shapes
            while plane.lag_records() > 0:
                await plane._refresh_once()
            # the timed leg is the refresh DRAIN, driven by manual rounds
            # (no refresh timer, no catch-up poll — both would quantize a
            # sub-ms drain): the transactional publish is identical across
            # arms and ~4x the refresh at the steady-ragged size, so
            # publish-inclusive rates are flat no matter what the dispatch
            # arm does.  Per-cycle rates + median: one scheduler/GC spike
            # on the 2-vCPU host must not decide a round.
            cyc_rates = []
            for batch in batches[1:]:
                publish(batch)
                t0 = time.perf_counter()
                while plane.lag_records() > 0:
                    await plane._refresh_once()
                cyc_rates.append(sum(n for _, n in batch)
                                 / (time.perf_counter() - t0))
            eps = med(cyc_rates)
            summ = ledger.summary()
            stages = ledger.round_stages_us()
            return eps, {
                "waste_ratio": summ["waste_ratio"],
                "us_per_slot": summ["us_per_slot"],
                "bucket_programs": summ["bucket_programs"],
                "bucket_fill_ratio": (
                    round(summ["lanes"] / summ["bucket_lane_slots"], 3)
                    if summ["bucket_lane_slots"] else None),
                "dispatch_us_median": (round(med(stages["dispatch_us"]))
                                       if stages["dispatch_us"] else 0),
            }
        finally:
            await plane.stop()

    out: dict = {"ragged_rounds": rounds, "ragged_cycles": cycles,
                 "protocol": {"interleaved": True, "medians": True}}
    arm_names = list(ARMS)
    per: dict = {s: {a: {"eps": [], "obs": []} for a in ARMS} for s in SHAPES}
    for rnd in range(rounds):
        order = arm_names[::-1] if rnd % 2 else arm_names
        for shape in SHAPES:
            for arm in order:
                eps, obs = asyncio.run(refresh_arm(arm, shape, seed=rnd))
                per[shape][arm]["eps"].append(eps)
                per[shape][arm]["obs"].append(obs)
    out["ragged_ladder"] = {}
    for shape in SHAPES:
        row = {}
        for arm in ARMS:
            eps_rounds = per[shape][arm]["eps"]
            obs = per[shape][arm]["obs"]
            row[arm] = {
                "events_per_sec_median": round(med(eps_rounds)),
                "rounds": [round(x) for x in eps_rounds],
                "waste_ratio": round(med(o["waste_ratio"] for o in obs), 2),
                "us_per_slot": round(med(o["us_per_slot"] for o in obs), 2),
                "dispatch_us_median": round(
                    med(o["dispatch_us_median"] for o in obs)),
                "bucket_fill_ratio": obs[0]["bucket_fill_ratio"],
            }
        row["bucketed_vs_dense"] = round(
            row["bucketed"]["events_per_sec_median"]
            / row["dense"]["events_per_sec_median"], 2)
        row["waste_reduction"] = round(
            row["dense"]["waste_ratio"]
            / row["bucketed"]["waste_ratio"], 2)
        row["bucketed_wins_every_round"] = all(
            b > d for b, d in zip(per[shape]["bucketed"]["eps"],
                                  per[shape]["dense"]["eps"]))
        out["ragged_ladder"][shape] = row
        log(f"ragged ladder [{shape}]: dense "
            f"{row['dense']['events_per_sec_median']} vs bucketed "
            f"{row['bucketed']['events_per_sec_median']} vs pallas "
            f"{row['bucketed_pallas']['events_per_sec_median']} ev/s; "
            f"waste {row['dense']['waste_ratio']}x -> "
            f"{row['bucketed']['waste_ratio']}x "
            f"({row['waste_reduction']}x less), bucketed wins every round: "
            f"{row['bucketed_wins_every_round']}")

    # -- the 1M-row donation probe (mesh-local, donate on vs off) -----------
    if run_probe:
        devs = jax.devices()
        assert len(devs) >= 8, (
            "ragged donation probe needs 8 forced host devices — main() "
            "must set xla_force_host_platform_device_count before jax init")
        mesh = jax.sharding.Mesh(np.array(devs[:8]), ("data",))
        probe_aggs = 512

        async def probe_arm(donate: bool):
            log_t, publish = make_arm_log(probe_aggs)
            ledger = ReplayLedger(name="bench:ragged:probe")
            plane = make_plane(log_t, probe_cap, ledger, {
                "surge.replay.donate-refresh": donate}, mesh=mesh)
            await plane.start()
            try:
                batch = [(f"agg-{i}", 2) for i in range(probe_aggs)]
                publish(batch)  # warm/compile outside the timed cycles
                while plane.lag_records() > 0:
                    await asyncio.sleep(0.002)
                warm_rounds = ledger.totals["rounds"]
                for _ in range(probe_cycles):
                    publish(batch)
                    while plane.lag_records() > 0:
                        await asyncio.sleep(0.002)
                # per-window device dispatch of the timed rounds only (the
                # warm cycle's rounds carry the compiles)
                per_window = [ev["dispatch_us"] / max(ev["windows"], 1)
                              for i, ev in enumerate(
                                  e for e in ledger.events()
                                  if e["type"] == "round")
                              if i >= warm_rounds]
                return {
                    "ms_per_window": round(med(per_window) / 1000.0, 2)
                    if per_window else 0.0,
                    "windows": int(ledger.totals["windows"]),
                }
            finally:
                await plane.stop()

        probe: dict = {"capacity": probe_cap, "donated": [], "copying": []}
        for rnd in range(rounds):
            order = ((False, True) if rnd % 2 else (True, False))
            for donate in order:
                r = asyncio.run(probe_arm(donate))
                probe["donated" if donate else "copying"].append(
                    r["ms_per_window"])
        out["donation_probe"] = {
            "capacity": probe_cap,
            "donated_ms_per_window": round(med(probe["donated"]), 2),
            "copying_ms_per_window": round(med(probe["copying"]), 2),
            "donated_vs_copying": round(
                med(probe["copying"]) / med(probe["donated"]), 2)
            if med(probe["donated"]) else 0.0,
            "round10_local_ms_per_window": 19.0,
            "donated_rounds": probe["donated"],
            "copying_rounds": probe["copying"],
        }
        p = out["donation_probe"]
        log(f"donation probe @{probe_cap} rows: donated "
            f"{p['donated_ms_per_window']} vs copying "
            f"{p['copying_ms_per_window']} ms/window "
            f"({p['donated_vs_copying']}x; round-10 undonated local "
            f"figure: 19 ms)")
    return out


def main() -> None:
    orig_env = dict(os.environ)
    # the parent NEVER initializes the tunneled backend — pin it to the host CPU
    # before any jax-importing module loads
    os.environ.update(_cpu_env(orig_env))
    for k in ("PALLAS_AXON_POOL_IPS", "AXON_POOL_IPS"):
        os.environ.pop(k, None)
    if (os.environ.get("SURGE_BENCH_MESH", "0") == "1"
            or os.environ.get("SURGE_BENCH_RAGGED", "0") == "1"):
        # the mesh arms (and the ragged bench's 1M-row donation probe) need
        # the tier-1 topology: force 8 host devices BEFORE the first jax
        # backend initialization (flag changes after init are silently
        # ignored)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()

    num_aggregates = int(os.environ.get("SURGE_BENCH_AGGREGATES", 1_000_000))
    num_events = int(os.environ.get("SURGE_BENCH_EVENTS", 100_000_000))
    cpu_sample_events = int(os.environ.get("SURGE_BENCH_CPU_SAMPLE", 200_000))

    import shutil
    import tempfile

    from surge_tpu.engine.model import fold_events
    from surge_tpu.models.counter import CounterModel
    from surge_tpu.replay.corpus import decode_sample, sample_indices, synth_counter_corpus

    payload: dict = {"metric": "cold_replay_events_per_sec", "value": 0,
                     "unit": "events/s", "vs_baseline": 0}

    # -- phase 2 first: steady-state latency (no accelerator, no corpus) ----------
    # running it before the corpus build keeps the multi-GB build/save churn
    # (page cache pressure, 1-core contention) out of the latency distribution
    try:
        latency_seconds = float(os.environ.get("SURGE_BENCH_LATENCY_SECONDS", 5))
    except ValueError:
        latency_seconds = 0.0
        payload["latency_error"] = "unparseable SURGE_BENCH_LATENCY_SECONDS"

    # SURGE_BENCH_LADDER=1: command-path fast path — regenerate the
    # throughput ladder + producer sweep WITHOUT the 100M-event corpus
    # build/replay (the replay numbers are untouched by producer work, and
    # the corpus build dominates a full run's wall clock)
    # SURGE_BENCH_FAILOVER=1: leader-kill chaos bench — unavailability
    # window + zero-loss/zero-duplicate proof, no corpus build
    if os.environ.get("SURGE_BENCH_FAILOVER", "0") == "1":
        payload = {"metric": "failover_unavailability_ms", "value": 0,
                   "unit": "ms"}
        stats = failover_bench()
        payload.update(stats)
        payload["value"] = stats.get("failover_unavailability_ms") or 0
        emit(payload)
        return

    # SURGE_BENCH_ANATOMY=1: traced command phase → the per-leg critical-path
    # attribution table alongside the phase's latency medians, so the next
    # perf PR starts from where-the-time-went evidence, not ladder guesses
    if os.environ.get("SURGE_BENCH_ANATOMY", "0") == "1":
        payload = {"metric": "command_p99_ms", "value": 0, "unit": "ms"}
        stats = anatomy_bench()
        payload.update(stats)
        payload["value"] = stats.get("command_p99_ms") or 0
        emit(payload)
        return

    # SURGE_BENCH_SOAK=1: sustained seeded chaos soak — a 3+-broker spread
    # cluster under rolling kills, link faults, membership churn and Zipf
    # skew, scored by the SLO engine; the verdict is 0 lost / 0 duplicated,
    # exactly one leader per partition, every burn-rate page cleared after
    # its heal, and the autobalancer's decisions on the merged timeline
    if os.environ.get("SURGE_BENCH_SOAK", "0") == "1":
        payload = {"metric": "soak_acked_commits", "value": 0, "unit": "ok"}
        stats = soak_bench()
        payload.update(stats)
        payload["value"] = stats.get("soak_acked_commits", 0)
        emit(payload)
        return

    # SURGE_BENCH_SAGA=1: the saga-storm chaos soak — hundreds of two-step
    # transfer sagas (a seeded fraction forced into the compensation walk)
    # vs rolling broker kills, link faults and a mid-storm manager restart;
    # the verdict is 0 lost / 0 duplicated / 0 half-compensated with the
    # ledger-reconciliation invariant checked per saga row
    if os.environ.get("SURGE_BENCH_SAGA", "0") == "1":
        payload = {"metric": "saga_started", "value": 0, "unit": "ok"}
        stats = saga_bench()
        payload.update(stats)
        payload["value"] = stats.get("saga_started", 0)
        emit(payload)
        return

    # SURGE_BENCH_HANDOFF=1: planned-handoff ladder — handoff vs
    # kill-failover vs full-replay cold start, paired interleaved medians
    if os.environ.get("SURGE_BENCH_HANDOFF", "0") == "1":
        payload = {"metric": "handoff_unavailability_ms", "value": 0,
                   "unit": "ms"}
        stats = handoff_bench()
        payload.update(stats)
        payload["value"] = stats.get("handoff_unavailability_ms_median") or 0
        emit(payload)
        return

    # SURGE_BENCH_MESH=1: mesh-native resident plane + sharded scans —
    # paired interleaved device-local vs replicated-slab arms (fold ladder +
    # read row) plus the query-engine sharded-scan throughput row
    if os.environ.get("SURGE_BENCH_MESH", "0") == "1":
        payload = {"metric": "mesh_fold_events_per_sec", "value": 0,
                   "unit": "events/s"}
        stats = mesh_bench()
        payload.update(stats)
        payload["value"] = max(r["local_events_per_sec"]
                               for r in stats["mesh_fold_ladder"])
        emit(payload)
        return

    # SURGE_BENCH_RAGGED=1: bucketed ragged refresh dispatch — paired
    # interleaved dense vs bucketed vs bucketed+pallas arms on the
    # steady-ragged and dense shapes, plus the 1M-row donation probe
    if os.environ.get("SURGE_BENCH_RAGGED", "0") == "1":
        payload = {"metric": "ragged_fold_events_per_sec", "value": 0,
                   "unit": "events/s"}
        stats = ragged_bench()
        payload.update(stats)
        payload["value"] = max(
            row["bucketed"]["events_per_sec_median"]
            for row in stats["ragged_ladder"].values())
        emit(payload)
        return

    # SURGE_BENCH_RESIDENT=1: device-resident read-plane fast path — read
    # ladder + refresh-loop folds + command guard, no corpus build. The full
    # corpus run below still replays through the resident layout by default;
    # SURGE_BENCH_STREAMING=1 (or the legacy SURGE_BENCH_RESIDENT=0) selects
    # the streaming window path there instead.
    if os.environ.get("SURGE_BENCH_RESIDENT", "0") == "1":
        payload = {"metric": "resident_reads_per_sec", "value": 0,
                   "unit": "reads/s"}
        stats = resident_bench()
        payload.update(stats)
        payload["value"] = max(r["device_reads_per_sec"]
                               for r in stats["resident_read_ladder"])
        emit(payload)
        return

    # SURGE_BENCH_RESIDENT_FEED=1: paired resident sustained-fold arms —
    # native feed vs per-event Python feed over the same FileLog tail
    if os.environ.get("SURGE_BENCH_RESIDENT_FEED", "0") == "1":
        payload = {"metric": "resident_feed_events_per_sec", "value": 0,
                   "unit": "events/s"}
        stats = resident_feed_paired()
        payload["resident_feed_paired"] = stats
        payload["value"] = stats["native_feed_events_per_sec_median"]
        emit(payload)
        return

    # SURGE_BENCH_VIEWS=1: paired interleaved materialized-view-read vs
    # scan-per-read reader ladder off the resident plane's refresh feed
    if os.environ.get("SURGE_BENCH_VIEWS", "0") == "1":
        payload = {"metric": "view_reads_per_sec", "value": 0,
                   "unit": "reads/s"}
        stats = views_paired()
        payload["views_paired"] = stats
        payload["value"] = max(
            r["view_read"]["reads_per_sec_median"] for r in stats["rungs"])
        emit(payload)
        return

    if os.environ.get("SURGE_BENCH_LADDER", "0") == "1":
        payload = {"metric": "commands_per_sec", "value": 0,
                   "unit": "commands/s"}
        secs = latency_seconds if latency_seconds > 0 else 5.0
        # SURGE_BENCH_LANE=1 (the r08 protocol): paired interleaved
        # direct-lane vs classic-lane medians, inproc AND grpc rungs
        if os.environ.get("SURGE_BENCH_LANE", "0") == "1":
            rounds = int(os.environ.get("SURGE_BENCH_LANE_ROUNDS", 3))
            rungs = [int(t) for t in os.environ.get(
                "SURGE_BENCH_LATENCY_LADDER", "").split(",")
                if t.strip().isdigit()] or [64, 1024]
            brokers = [b.strip() for b in os.environ.get(
                "SURGE_BENCH_LANE_BROKERS", "inproc,grpc").split(",")
                if b.strip()]
            paired = lane_paired_ladder(secs, rounds=rounds, rungs=rungs,
                                        brokers=brokers)
            payload["lane_paired_ladder"] = paired
            payload["value"] = max(
                r["direct"]["commands_per_sec_median"]
                for rows in paired["ladders"].values() for r in rows)
            emit(payload)
            return
        # SURGE_BENCH_NATIVE=1 (the r07 protocol): paired interleaved
        # native-on vs native-off medians at the 64 + 1024 rungs
        if os.environ.get("SURGE_BENCH_NATIVE", "0") == "1":
            rounds = int(os.environ.get("SURGE_BENCH_NATIVE_ROUNDS", 3))
            rungs = [int(t) for t in os.environ.get(
                "SURGE_BENCH_LATENCY_LADDER", "").split(",")
                if t.strip().isdigit()] or [64, 1024]
            paired = native_paired_ladder(
                secs, rounds=rounds, rungs=rungs,
                broker=os.environ.get("SURGE_BENCH_NATIVE_BROKER", "inproc"))
            payload["native_paired_ladder"] = paired
            payload["value"] = max(
                r["native_on"]["commands_per_sec_median"]
                for r in paired["rungs"])
            emit(payload)
            return
        stats = steady_state_latency(secs)
        payload.update(stats)
        payload["value"] = stats["peak_commands_per_sec"]
        log(f"ladder fast path: p50 {stats['command_p50_ms']}ms at "
            f"{stats['latency_workers']} workers, peak "
            f"{stats['peak_commands_per_sec']} commands/s")
        if os.environ.get("SURGE_BENCH_SWEEP", "1") == "1":
            payload["producer_sweep"] = producer_sweep(secs)
        emit(payload)
        return

    if latency_seconds > 0:
        try:
            stats = steady_state_latency(latency_seconds)
            log(f"steady state: p50 {stats['command_p50_ms']}ms, "
                f"p99 {stats['command_p99_ms']}ms, "
                f"{stats['commands_per_sec']} commands/s")
            payload.update(stats)
            if os.environ.get("SURGE_BENCH_SWEEP", "1") == "1":
                try:
                    payload["producer_sweep"] = producer_sweep(latency_seconds)
                except Exception as exc:  # noqa: BLE001
                    log(f"producer sweep failed: {exc!r}")
                    payload["sweep_error"] = f"{type(exc).__name__}: {exc}"
        except Exception as exc:  # noqa: BLE001 — phase 2 must not void phase 1
            log(f"steady-state latency phase failed: {exc!r}")
            payload["latency_error"] = f"{type(exc).__name__}: {exc}"

    # -- optional restore phase: full vs checkpointed cold start ------------------
    if os.environ.get("SURGE_BENCH_RESTORE", "0") == "1":
        try:
            payload.update(restore_bench())
        except Exception as exc:  # noqa: BLE001 — must not void the headline
            log(f"restore bench phase failed: {exc!r}")
            payload["restore_error"] = f"{type(exc).__name__}: {exc}"

    t0 = time.perf_counter()
    corpus = synth_counter_corpus(num_aggregates, num_events, seed=42,
                                  sort_by_length=True)
    build_s = time.perf_counter() - t0
    log(f"corpus: {corpus.num_aggregates} aggregates, {corpus.num_events} events, "
        f"{corpus.events.nbytes() / 1e9:.2f} GB columnar ({build_s:.1f}s)")
    payload.update(num_events=corpus.num_events, num_aggregates=corpus.num_aggregates,
                   corpus_build_s=round(build_s, 1))

    corpus_dir = tempfile.mkdtemp(prefix="surge-bench-corpus-")
    try:
        t0 = time.perf_counter()
        save_corpus(corpus, corpus_dir)
        log(f"corpus saved to {corpus_dir} ({time.perf_counter() - t0:.1f}s)")

        # one-time wire pack (the log-segment build analog, SURVEY §5.4): cold
        # replays mmap this and stream it straight onto the device. Skipped
        # when the streaming path is benched — no child would read it.
        if (os.environ.get("SURGE_BENCH_STREAMING", "0") != "1"
                and os.environ.get("SURGE_BENCH_RESIDENT", "1") == "1"):
            t0 = time.perf_counter()
            make_engine().pack_resident(corpus.events).save(
                os.path.join(corpus_dir, "wire"))
            wire_pack_s = time.perf_counter() - t0
            log(f"wire packed+saved ({wire_pack_s:.1f}s, one-time build)")
            payload["wire_pack_s"] = round(wire_pack_s, 1)

        # -- scalar CPU fold baseline (the reference restore path) --------------------
        idx = sample_indices(corpus, cpu_sample_events)
        logs = decode_sample(corpus, idx)
        n_sample = sum(len(l) for l in logs)
        model = CounterModel()
        t0 = time.perf_counter()
        folded = [fold_events(model, None, events) for events in logs]
        cpu_s = time.perf_counter() - t0
        cpu_eps = n_sample / cpu_s
        # golden cross-check: scalar fold must agree with the closed-form expectation
        for j, state in zip(idx, folded):
            expect = (int(corpus.expected_count[j]), int(corpus.expected_version[j]))
            got = (state.count, state.version) if state is not None else (0, 0)
            if got != expect:
                raise AssertionError(
                    f"scalar fold mismatch at aggregate {j}: {got} != {expect}")
        log(f"cpu baseline: {n_sample} events over {len(logs)} aggregates in "
            f"{cpu_s:.2f}s -> {cpu_eps:,.0f} events/s (verified)")
        payload["cpu_baseline_events_per_sec"] = round(cpu_eps)

        # the corpus lives on disk now; free the ~1.6 GB in-memory copy (and the
        # decoded sample) before replay children map the same data
        del corpus, logs, folded

        # -- CPU-JAX batched replay (provisional headline) ----------------------------
        if os.environ.get("SURGE_BENCH_SKIP_CPU_REPLAY", "0") != "1":
            cpu_child = run_replay_child(_cpu_env(orig_env), corpus_dir, "cpu")
            if cpu_child is not None:
                _merge_replay(payload, cpu_child, cpu_eps)
                payload["cpu_jax_events_per_sec"] = cpu_child["events_per_sec"]
            else:
                payload["cpu_replay_error"] = "cpu replay child failed (see stderr)"
        # PROVISIONAL line: from here on the round has a real measured number no
        # matter what the TPU attempt does (last line wins for the driver)
        emit(payload)

        # -- ONE patient TPU attempt (never killed) -----------------------------------
        tpu_possible = (orig_env.get("PALLAS_AXON_POOL_IPS")
                        or orig_env.get("AXON_POOL_IPS")
                        or orig_env.get("JAX_PLATFORMS", "") not in ("", "cpu"))
        if os.environ.get("SURGE_BENCH_TPU", "1") == "1" and tpu_possible:
            tpu_child = run_replay_child(dict(orig_env), corpus_dir, "tpu")
            if tpu_child is not None and tpu_child["platform"] != "cpu":
                # record the silicon numbers unconditionally; the HEADLINE
                # takes the platform whose END-TO-END cold replay is faster.
                # Through this tunnel the cold path is transfer-bound (h2d +
                # the ~25 MB/s d2h state pull), so the host can win cold while
                # the chip wins the steady resident regime by ~2× and the pure
                # fold by ~25× — all three are recorded (docs/roofline.md)
                for k in ("events_per_sec", "replay_s", "steady_replay_s",
                          "steady_events_per_sec", "device_fold_events_per_sec",
                          "upload_s", "densify_s", "fold_s", "pad_ratio",
                          "knobs"):
                    if k in tpu_child:
                        payload[f"tpu_{k}"] = tpu_child[k]
                if cpu_eps and tpu_child.get("steady_events_per_sec"):
                    payload["vs_baseline_tpu_steady"] = round(
                        tpu_child["steady_events_per_sec"] / cpu_eps, 2)
                if tpu_child["events_per_sec"] >= payload["value"]:
                    _merge_replay(payload, tpu_child, cpu_eps)
                else:
                    log("tpu cold end-to-end below the host number; headline "
                        "stays cpu (tpu_* fields + BENCH_ONCHIP.json carry "
                        "the silicon evidence)")
                log(f"speedup vs scalar CPU fold: {payload['vs_baseline']}x "
                    f"cold on {payload['platform']}; tpu steady "
                    f"{payload.get('vs_baseline_tpu_steady', 0)}x "
                    f"(target >=50x)")
                emit(payload)
            elif tpu_child is not None:
                log("tpu child came up on cpu; keeping provisional result")
            else:
                payload["tpu_error"] = "tpu replay child failed (see stderr)"
                emit(payload)
            # bank the BENCH_ONCHIP.json sweep in its OWN subprocess now that
            # the child released the device: a fresh runtime keeps the
            # artifact's probe/fold numbers clean (an in-process sweep both
            # degrades later uploads ~10× and, run after the measurement,
            # banks degraded numbers itself). Only when the child actually
            # reached silicon — if its claim hung into UNAVAILABLE, a sweep
            # attempt would just hang the same ~25 min again
            if (os.environ.get("SURGE_BENCH_ONCHIP", "1") == "1"
                    and tpu_child is not None
                    and tpu_child["platform"] != "cpu"):
                log("banking on-chip sweep artifact (separate process)...")
                sweep = subprocess.run(
                    [sys.executable,
                     os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                  "onchip_sweep.py"), corpus_dir],
                    env=dict(orig_env), stdout=subprocess.DEVNULL)
                log(f"on-chip sweep exited rc={sweep.returncode} "
                    "(BENCH_ONCHIP.json)")
        elif not tpu_possible:
            log("no accelerator platform configured in the environment; done")
    finally:
        shutil.rmtree(corpus_dir, ignore_errors=True)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--replay-child":
        try:
            replay_child(sys.argv[2])
        except BaseException:
            import traceback

            traceback.print_exc(file=sys.stderr)
            sys.exit(1)
        sys.exit(0)
    try:
        main()
    except BaseException as err:  # terminal failure must still emit one JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        # never clobber an already-measured result with a value-0 line: re-emit the
        # last printed payload with the error attached (last line wins)
        final = dict(_last_printed) if _last_printed else {
            "metric": "cold_replay_events_per_sec", "value": 0,
            "unit": "events/s", "vs_baseline": 0}
        final["error"] = f"{type(err).__name__}: {err}"
        print(json.dumps(final), flush=True)
        sys.exit(1)
