#!/usr/bin/env python
"""North-star benchmark: cold replay of a ragged event log (BASELINE.md targets).

Phase 1 (replay): builds a 1M-aggregate / 100M-event counter corpus columnar-side (no
Python event objects), measures the scalar CPU fold baseline on a stratified sample
(the reference's Kafka Streams restore is exactly this per-aggregate scalar fold,
SURVEY.md §3.3), then runs the batched TPU replay over the full corpus and verifies
every folded state against the closed-form expected result.

Phase 2 (steady state): p50/p99 send_command latency and commands/sec through the full
engine (router → entity → transactional publisher with the reference's 50 ms flush
tick → durable FileLog with fsync-on-commit) — the second BASELINE.md target; the
reference's envelope is flush-interval + txn commit.

Prints ONE JSON line to stdout:
    {"metric": "cold_replay_events_per_sec", "value": N, "unit": "events/s",
     "vs_baseline": <speedup over the scalar CPU fold>,
     "command_p50_ms": ..., "command_p99_ms": ..., "commands_per_sec": ...}

Env knobs: SURGE_BENCH_AGGREGATES (1_000_000), SURGE_BENCH_EVENTS (100_000_000),
SURGE_BENCH_CPU_SAMPLE (200_000 events), SURGE_BENCH_TIME_CHUNK, SURGE_BENCH_BATCH,
SURGE_BENCH_LATENCY_SECONDS (5; 0 skips phase 2), SURGE_BENCH_LATENCY_WORKERS (64).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def acquire_backend():
    """Bounded-retry backend bring-up with CPU fallback (VERDICT r2 weak #1).

    The tunneled TPU backend can be transiently UNAVAILABLE; one hiccup must not cost
    the round's only data point. Retry acquisition (jax re-attempts init while no
    backend exists), then fall back to the host CPU platform so the bench still emits
    a real measured number with the platform honestly reported.
    """
    attempts = int(os.environ.get("SURGE_BENCH_BACKEND_ATTEMPTS", 5))
    backoff_s = float(os.environ.get("SURGE_BENCH_BACKEND_BACKOFF_S", 60))
    # one tunneled bring-up ATTEMPT has been observed to take ~25 minutes before
    # failing UNAVAILABLE — a wall-clock deadline bounds total acquisition time so
    # retries cannot eat the whole bench window before the CPU fallback runs
    deadline_s = float(os.environ.get("SURGE_BENCH_BACKEND_DEADLINE_S", 2400))

    import jax

    from jax.extend.backend import clear_backends

    t_start = time.monotonic()
    last_err = None
    for attempt in range(1, attempts + 1):
        try:
            devices = jax.devices()
            log(f"backend up on attempt {attempt}: {devices}")
            return jax, devices
        except Exception as err:
            last_err = err
            elapsed = time.monotonic() - t_start
            log(f"backend attempt {attempt}/{attempts} failed after "
                f"{elapsed:.0f}s total: {err}")
            if attempt < attempts and elapsed + backoff_s < deadline_s:
                # a failed bring-up can leave partially-initialized backends cached
                # (e.g. cpu registered before the tpu factory raised) — clear so the
                # next attempt genuinely re-initializes the target platform
                clear_backends()
                time.sleep(backoff_s)
            else:
                break

    log(f"giving up on the default platform, falling back to cpu: {last_err}")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.pop("AXON_POOL_IPS", None)
    clear_backends()
    jax.config.update("jax_platforms", "cpu")
    devices = jax.devices()  # raises only if even the host CPU platform is broken
    return jax, devices


def steady_state_latency(seconds: float) -> dict:
    """Phase 2: the full command path on one node, reference-default envelope.

    Concurrent per-aggregate workers issue sequential Increment commands through
    ``aggregate_for().send_command`` against a FileLog (fsync on commit) with the
    50 ms flush tick, so each command's latency = handling + wait-for-tick + one
    durable transaction commit — directly comparable to the reference's
    flush-interval + Kafka txn commit envelope (core reference.conf:20-21).
    """
    import asyncio
    import shutil
    import tempfile

    from surge_tpu import (
        CommandSuccess,
        SurgeCommandBusinessLogic,
        create_engine,
        default_config,
    )
    from surge_tpu.log.file import FileLog
    from surge_tpu.models import counter

    workers = int(os.environ.get("SURGE_BENCH_LATENCY_WORKERS", 64))
    flush_ms = default_config().get_int("surge.producer.flush-interval-ms")
    root = tempfile.mkdtemp(prefix="surge-bench-latency-")

    async def scenario() -> dict:
        log = FileLog(os.path.join(root, "log"))
        engine = create_engine(
            SurgeCommandBusinessLogic(
                aggregate_name="counter", model=counter.CounterModel(),
                state_format=counter.state_formatting(),
                event_format=counter.event_formatting()),
            log=log, config=default_config())
        await engine.start()

        latencies: list = []

        async def worker(i: int, stop_at: float) -> None:
            agg = f"bench-{i}"
            ref = engine.aggregate_for(agg)
            while time.perf_counter() < stop_at:
                t0 = time.perf_counter()
                r = await ref.send_command(counter.Increment(agg))
                if not isinstance(r, CommandSuccess):
                    raise RuntimeError(f"command failed: {r}")
                latencies.append(time.perf_counter() - t0)

        # warmup (entity init + first flushes), then the measured window
        await asyncio.gather(*(worker(i, time.perf_counter() + 1.0)
                               for i in range(workers)))
        latencies.clear()
        t0 = time.perf_counter()
        await asyncio.gather(*(worker(i, t0 + seconds) for i in range(workers)))
        elapsed = time.perf_counter() - t0
        await engine.stop()
        log.close()

        lat_ms = sorted(1000.0 * x for x in latencies)
        n = len(lat_ms)
        return {
            "command_p50_ms": round(lat_ms[n // 2], 2),
            "command_p99_ms": round(lat_ms[min(n - 1, (99 * n) // 100)], 2),
            "commands_per_sec": round(n / elapsed),
            "latency_commands": n,
            "latency_workers": workers,
            "flush_interval_ms": flush_ms,
        }

    try:
        return asyncio.run(scenario())
    finally:
        shutil.rmtree(root, ignore_errors=True)


def main() -> None:
    num_aggregates = int(os.environ.get("SURGE_BENCH_AGGREGATES", 1_000_000))
    num_events = int(os.environ.get("SURGE_BENCH_EVENTS", 100_000_000))
    cpu_sample_events = int(os.environ.get("SURGE_BENCH_CPU_SAMPLE", 200_000))
    time_chunk = int(os.environ.get("SURGE_BENCH_TIME_CHUNK", 128))
    batch_size = int(os.environ.get("SURGE_BENCH_BATCH", 8192))

    jax, devices = acquire_backend()

    from surge_tpu.config import default_config
    from surge_tpu.engine.model import fold_events
    from surge_tpu.models.counter import CounterModel, make_replay_spec
    from surge_tpu.replay.corpus import decode_sample, sample_indices, synth_counter_corpus
    from surge_tpu.replay.engine import ReplayEngine

    platform = devices[0].platform
    log(f"platform={platform} devices={devices}")

    t0 = time.perf_counter()
    corpus = synth_counter_corpus(num_aggregates, num_events, seed=42,
                                  sort_by_length=True)
    log(f"corpus: {corpus.num_aggregates} aggregates, {corpus.num_events} events, "
        f"{corpus.events.nbytes() / 1e9:.2f} GB columnar "
        f"({time.perf_counter() - t0:.1f}s)")

    # -- scalar CPU fold baseline (the reference restore path) ------------------------
    idx = sample_indices(corpus, cpu_sample_events)
    logs = decode_sample(corpus, idx)
    n_sample = sum(len(l) for l in logs)
    model = CounterModel()
    t0 = time.perf_counter()
    folded = [fold_events(model, None, events) for events in logs]
    cpu_s = time.perf_counter() - t0
    cpu_eps = n_sample / cpu_s
    # golden cross-check: the scalar fold must agree with the closed-form expectation
    for j, state in zip(idx, folded):
        expect_c, expect_v = int(corpus.expected_count[j]), int(corpus.expected_version[j])
        got_c = state.count if state is not None else 0
        got_v = state.version if state is not None else 0
        if got_c != expect_c or got_v != expect_v:
            raise AssertionError(
                f"scalar fold mismatch at aggregate {j}: "
                f"({got_c},{got_v}) != ({expect_c},{expect_v})")
    log(f"cpu baseline: {n_sample} events over {len(logs)} aggregates in {cpu_s:.2f}s "
        f"-> {cpu_eps:,.0f} events/s (verified)")

    # -- batched TPU replay ------------------------------------------------------------
    cfg = default_config().with_overrides({
        "surge.replay.batch-size": batch_size,
        "surge.replay.time-chunk": time_chunk,
    })
    engine = ReplayEngine(make_replay_spec(), config=cfg)

    # warm up the one compiled program (shapes are fixed [time_chunk, batch_size])
    warm = synth_counter_corpus(min(batch_size, num_aggregates),
                                min(batch_size * 4, num_events), seed=1)
    engine.replay_columnar(warm.events)
    log(f"warmup done, compiled programs: {engine.num_compiles()}")

    t0 = time.perf_counter()
    result = engine.replay_columnar(corpus.events)
    replay_s = time.perf_counter() - t0
    eps = corpus.num_events / replay_s
    aps = corpus.num_aggregates / replay_s

    if not np.array_equal(result.states["count"], corpus.expected_count):
        raise AssertionError("replay count mismatch vs closed-form fold")
    if not np.array_equal(result.states["version"], corpus.expected_version):
        raise AssertionError("replay version mismatch vs closed-form fold")
    if result.num_events != corpus.num_events:
        raise AssertionError("replay event accounting mismatch")

    speedup = eps / cpu_eps
    pad_ratio = result.padded_events / max(corpus.num_events, 1)
    log(f"replay: {corpus.num_events:,} events / {corpus.num_aggregates:,} aggregates "
        f"in {replay_s:.2f}s -> {eps:,.0f} events/s, {aps:,.0f} aggregates/s "
        f"(pad ratio {pad_ratio:.2f}, compiles {engine.num_compiles()}, verified)")
    log(f"speedup vs scalar CPU fold: {speedup:.1f}x (target >=50x)")

    payload = {
        "metric": "cold_replay_events_per_sec",
        "value": round(eps),
        "unit": "events/s",
        "vs_baseline": round(speedup, 2),
        "aggregates_per_sec": round(aps),
        "cpu_baseline_events_per_sec": round(cpu_eps),
        "num_events": corpus.num_events,
        "num_aggregates": corpus.num_aggregates,
        "pad_ratio": round(pad_ratio, 3),
        "platform": platform,
    }

    try:
        latency_seconds = float(os.environ.get("SURGE_BENCH_LATENCY_SECONDS", 5))
    except ValueError:
        latency_seconds = 0.0
        payload["latency_error"] = "unparseable SURGE_BENCH_LATENCY_SECONDS"
    if latency_seconds > 0:
        try:
            stats = steady_state_latency(latency_seconds)
            log(f"steady state: p50 {stats['command_p50_ms']}ms, "
                f"p99 {stats['command_p99_ms']}ms, "
                f"{stats['commands_per_sec']} commands/s "
                f"({stats['latency_workers']} workers, "
                f"{stats['flush_interval_ms']}ms flush, fsync commit)")
            payload.update(stats)
        except Exception as exc:  # noqa: BLE001 — phase 2 must not void phase 1
            log(f"steady-state latency phase failed: {exc!r}")
            payload["latency_error"] = f"{type(exc).__name__}: {exc}"

    print(json.dumps(payload), flush=True)


if __name__ == "__main__":
    try:
        main()
    except BaseException as err:  # terminal failure must still emit one JSON line
        import traceback

        traceback.print_exc(file=sys.stderr)
        print(json.dumps({
            "metric": "cold_replay_events_per_sec",
            "value": 0,
            "unit": "events/s",
            "vs_baseline": 0,
            "error": f"{type(err).__name__}: {err}",
        }), flush=True)
        sys.exit(1)
