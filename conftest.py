"""Root conftest: force a virtual 8-device CPU platform for all tests.

Real-TPU execution happens only in bench.py / __graft_entry__.entry(); tests exercise the
multi-device sharding paths on the host (xla_force_host_platform_device_count), per the
driver contract.

The image's sitecustomize imports jax and registers the tunneled TPU backend before
pytest starts, so plain env-var setdefaults are too late — we must update jax.config
directly (safe as long as no backend has been initialized yet, which conftest import
time guarantees).
"""

import os

_platform = os.environ.get("SURGE_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", _platform)


def pytest_configure(config):
    # the tier-1 budget rests on `-m 'not slow'`: register the marker so a
    # typo'd @pytest.mark.sloow fails the -W error audit instead of silently
    # joining tier-1 (chaos soaks and minutes-long benches must stay out)
    config.addinivalue_line(
        "markers", "slow: minutes-long soak/bench tests excluded from the "
                   "tier-1 `-m 'not slow'` run")
    # build the csrc/ native libraries once per session when a compiler is
    # present (incremental — ~free when up to date), so tier-1 exercises the
    # native hot path instead of always taking the Python fallback. Without
    # a compiler the libraries stay absent and native-only tests skip with
    # a reason (see tests/test_native_gate.py / test_abi_drift.py).
    import shutil
    import subprocess

    if (shutil.which("g++")
            and os.environ.get("SURGE_SKIP_NATIVE_BUILD", "0") != "1"):
        build = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "csrc", "build.sh")
        try:
            proc = subprocess.run(["sh", build], capture_output=True,
                                  timeout=120)
            if proc.returncode != 0:
                print(f"csrc/build.sh failed (native tests will skip): "
                      f"{proc.stderr.decode(errors='replace')[-500:]}")
        except Exception as exc:  # noqa: BLE001 — the build is best-effort
            print(f"csrc/build.sh unavailable: {exc!r}")


def free_ports(n: int = 1) -> list:
    """Distinct ephemeral ports: all sockets stay bound until every port is
    chosen, so two consecutive calls cannot hand back the same port."""
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()
