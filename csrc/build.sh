#!/bin/sh
# Build the native libraries into csrc/build/ (picked up by surge_tpu.store.native,
# surge_tpu.log.segment and surge_tpu.log.native_gate via ctypes). Requires only
# g++; no external dependencies.
#
# Incremental: a library is rebuilt only when one of its sources is newer than
# the built .so, so conftest can invoke this once per test session for ~free.
# Link to a UNIQUE temp name (PID-suffixed: concurrent sessions both running
# this script must not interleave writes into one tmp) then atomically rename,
# so a process that has the current .so dlopen'd never sees a truncated file.
set -e
cd "$(dirname "$0")"
mkdir -p build

stale() {  # stale <target> <src>... -> 0 (build needed) | 1 (up to date)
  target="$1"
  shift
  [ -f "$target" ] || return 0
  for src in "$@"; do
    [ "$src" -nt "$target" ] && return 0
  done
  return 1
}

built=""
if stale build/libsurge_store.so store.cc; then
  g++ -O2 -std=c++17 -shared -fPIC -Wall -o "build/.libsurge_store.so.tmp.$$" store.cc
  mv "build/.libsurge_store.so.tmp.$$" build/libsurge_store.so
  built="$built libsurge_store.so"
fi
if [ -f segment.cc ] && stale build/libsurge_segment.so segment.cc; then
  g++ -O2 -std=c++17 -shared -fPIC -Wall -o "build/.libsurge_segment.so.tmp.$$" segment.cc
  mv "build/.libsurge_segment.so.tmp.$$" build/libsurge_segment.so
  built="$built libsurge_segment.so"
fi
# txn.cc links segment.cc in, so its block bytes are identical-by-construction
# with the standalone segment codec
if [ -f txn.cc ] && stale build/libsurge_txn.so txn.cc segment.cc; then
  g++ -O2 -std=c++17 -shared -fPIC -Wall -o "build/.libsurge_txn.so.tmp.$$" txn.cc segment.cc
  mv "build/.libsurge_txn.so.tmp.$$" build/libsurge_txn.so
  built="$built libsurge_txn.so"
fi
if [ -n "$built" ]; then
  echo "built:$built"
else
  echo "up to date: $(ls build)"
fi
