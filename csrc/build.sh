#!/bin/sh
# Build the native libraries into csrc/build/ (picked up by surge_tpu.store.native and
# surge_tpu.log.segment via ctypes). Requires only g++; no external dependencies.
set -e
cd "$(dirname "$0")"
mkdir -p build
# Link to a temp name then atomically rename, so a process that has the current .so
# dlopen'd never sees a truncated file.
g++ -O2 -std=c++17 -shared -fPIC -Wall -o build/.libsurge_store.so.tmp store.cc
mv build/.libsurge_store.so.tmp build/libsurge_store.so
if [ -f segment.cc ]; then
  g++ -O2 -std=c++17 -shared -fPIC -Wall -o build/.libsurge_segment.so.tmp segment.cc
  mv build/.libsurge_segment.so.tmp build/libsurge_segment.so
fi
echo "built: $(ls build)"
