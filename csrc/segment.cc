// Block codec for log segment files: LZ-style compression + CRC32.
//
// Fills the role of the reference's native Kafka compression codecs (lz4/zstd JNI,
// producer default compression-type=lz4 — SURVEY.md §2.9 item 2): log blocks are
// compressed in C++ on the append path and decompressed on the read path, via ctypes
// from surge_tpu/log/segment.py.
//
// Format ("SLZ1", not LZ4-compatible): a sequence of ops. Each op starts with a token
// byte: high nibble = literal length, low nibble = match length - kMinMatch. Length
// nibbles of 15 extend with 255-run bytes (like LZ4's varint scheme). Literals follow
// the token; a match follows as a 2-byte little-endian back-offset (1..65535) into the
// already-produced output. A final op may have match length nibble 0 meaning
// "literals only, end of stream". Matching uses a 4-byte-hash greedy parser.

#include <cstdint>
#include <cstring>

namespace {

constexpr int kMinMatch = 4;
constexpr int kHashBits = 15;
constexpr int kMaxOffset = 65535;

inline uint32_t Hash4(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return (v * 2654435761u) >> (32 - kHashBits);
}

inline void PutLength(uint8_t*& op, size_t len) {
  while (len >= 255) {
    *op++ = 255;
    len -= 255;
  }
  *op++ = static_cast<uint8_t>(len);
}

}  // namespace

extern "C" {

// Worst-case output size for n input bytes (all literals + token overhead).
size_t surge_lz_bound(size_t n) { return n + n / 255 + 16; }

// Returns compressed size, or 0 if dst_cap is too small (caller should then store
// the block uncompressed).
size_t surge_lz_compress(const uint8_t* src, size_t n, uint8_t* dst, size_t dst_cap) {
  if (dst_cap < surge_lz_bound(n)) return 0;
  if (n == 0) {
    dst[0] = 0;
    return 1;
  }
  static thread_local uint32_t table[1u << kHashBits];
  std::memset(table, 0, sizeof(table));

  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  const uint8_t* const mflimit = (n >= 12) ? iend - 11 : src;  // last safe match start
  const uint8_t* anchor = src;
  uint8_t* op = dst;

  while (ip < mflimit) {
    // find a match via the 4-byte hash table
    uint32_t h = Hash4(ip);
    const uint8_t* ref = src + table[h];
    table[h] = static_cast<uint32_t>(ip - src);
    if (ref >= ip || ip - ref > kMaxOffset || ref < src ||
        std::memcmp(ref, ip, kMinMatch) != 0) {
      ++ip;
      continue;
    }
    // extend the match forward
    const uint8_t* mp = ref + kMinMatch;
    const uint8_t* p = ip + kMinMatch;
    while (p < iend && *p == *mp) ++p, ++mp;
    size_t match_len = p - ip;
    size_t lit_len = ip - anchor;

    // op layout (must mirror the decoder): token, literal-length extension,
    // literals, match-length extension, offset. The match nibble is stored +1 so
    // 0 can mean "end of stream".
    size_t ml_code = match_len - kMinMatch;
    size_t ml_nibble = (ml_code < 14) ? ml_code + 1 : 15;
    *op++ = static_cast<uint8_t>(((lit_len < 15 ? lit_len : 15) << 4) | ml_nibble);
    if (lit_len >= 15) PutLength(op, lit_len - 15);
    std::memcpy(op, anchor, lit_len);
    op += lit_len;
    if (ml_nibble == 15) PutLength(op, ml_code - 14);
    uint16_t off = static_cast<uint16_t>(ip - ref);
    *op++ = static_cast<uint8_t>(off & 0xFF);
    *op++ = static_cast<uint8_t>(off >> 8);

    ip += match_len;
    anchor = ip;
    if (ip < mflimit) table[Hash4(ip - 2)] = static_cast<uint32_t>(ip - 2 - src);
  }

  // trailing literals, match nibble 0 = end
  size_t lit_len = iend - anchor;
  uint8_t token = static_cast<uint8_t>((lit_len < 15 ? lit_len : 15) << 4);
  *op++ = token;
  if (lit_len >= 15) PutLength(op, lit_len - 15);
  std::memcpy(op, anchor, lit_len);
  op += lit_len;
  return static_cast<size_t>(op - dst);
}

// Returns decompressed size, or 0 on malformed/overflowing input.
size_t surge_lz_decompress(const uint8_t* src, size_t n, uint8_t* dst,
                           size_t dst_cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;

  while (ip < iend) {
    uint8_t token = *ip++;
    size_t lit_len = token >> 4;
    size_t ml_nibble = token & 0x0F;
    if (lit_len == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return 0;
        b = *ip++;
        lit_len += b;
      } while (b == 255);
    }
    if (ip + lit_len > iend || op + lit_len > oend) return 0;
    std::memcpy(op, ip, lit_len);
    ip += lit_len;
    op += lit_len;
    if (ml_nibble == 0) break;  // end of stream
    size_t ml_code = ml_nibble - 1;
    if (ml_nibble == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return 0;
        b = *ip++;
        ml_code += b;
      } while (b == 255);
    }
    size_t match_len = ml_code + kMinMatch;
    if (ip + 2 > iend) return 0;
    size_t off = ip[0] | (static_cast<size_t>(ip[1]) << 8);
    ip += 2;
    if (off == 0 || static_cast<size_t>(op - dst) < off) return 0;
    if (op + match_len > oend) return 0;
    const uint8_t* ref = op - off;
    for (size_t i = 0; i < match_len; ++i) op[i] = ref[i];  // overlapping copy
    op += match_len;
  }
  return static_cast<size_t>(op - dst);
}

uint32_t surge_crc32(const uint8_t* src, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t crc = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i) crc = table[(crc ^ src[i]) & 0xFF] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

}  // extern "C"
