// Open-addressing hash KV store — the native state-store backend.
//
// Replaces the reference's RocksDB JNI dependency (SurgeKafkaStreamsPersistencePlugin
// .scala:17-22) for the materialized-state read path: the engine's steady-state access
// pattern is point get/put by aggregate id (KafkaStreamManagerActor.scala:89-91), which
// an in-process open-addressing table serves with no JNI/FFI marshalling beyond ctypes.
//
// Layout: one flat slot array (linear probing, power-of-two capacity, tombstones),
// keys+values owned by the slots as length-prefixed byte strings. Load factor <= 0.7;
// tombstone compaction happens on grow. Not thread-safe by design: the engine drives
// each store from a single asyncio loop (single-writer, like the Kafka Streams task
// thread owning a RocksDB shard).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Slot {
  std::string key;
  std::string value;
  uint64_t hash = 0;
  enum State : uint8_t { kEmpty = 0, kUsed = 1, kTombstone = 2 } state = kEmpty;
};

uint64_t fnv1a(const char* data, size_t n) {
  uint64_t h = 1469598103934665603ull;
  for (size_t i = 0; i < n; ++i) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= 1099511628211ull;
  }
  return h;
}

class Store {
 public:
  Store() : slots_(kInitialCapacity) {}

  void Put(const char* key, size_t klen, const char* val, size_t vlen) {
    MaybeGrow();
    const uint64_t h = fnv1a(key, klen);
    Slot* slot = FindForInsert(key, klen, h);
    if (slot->state != Slot::kUsed) {
      if (slot->state == Slot::kTombstone) --tombstones_;
      slot->key.assign(key, klen);
      slot->hash = h;
      slot->state = Slot::kUsed;
      ++size_;
    }
    slot->value.assign(val, vlen);
  }

  const std::string* Get(const char* key, size_t klen) const {
    const Slot* slot = Find(key, klen);
    return slot ? &slot->value : nullptr;
  }

  void Delete(const char* key, size_t klen) {
    Slot* slot = const_cast<Slot*>(Find(key, klen));
    if (slot == nullptr) return;
    slot->key.clear();
    slot->value.clear();
    slot->state = Slot::kTombstone;
    --size_;
    ++tombstones_;
  }

  size_t Size() const { return size_; }

  void Clear() {
    slots_.assign(kInitialCapacity, Slot());
    size_ = 0;
    tombstones_ = 0;
  }

  const std::vector<Slot>& slots() const { return slots_; }

 private:
  static constexpr size_t kInitialCapacity = 1024;  // power of two

  const Slot* Find(const char* key, size_t klen) const {
    const uint64_t h = fnv1a(key, klen);
    const size_t mask = slots_.size() - 1;
    for (size_t i = h & mask, probes = 0; probes < slots_.size();
         i = (i + 1) & mask, ++probes) {
      const Slot& s = slots_[i];
      if (s.state == Slot::kEmpty) return nullptr;
      if (s.state == Slot::kUsed && s.hash == h && s.key.size() == klen &&
          std::memcmp(s.key.data(), key, klen) == 0) {
        return &s;
      }
    }
    return nullptr;
  }

  Slot* FindForInsert(const char* key, size_t klen, uint64_t h) {
    const size_t mask = slots_.size() - 1;
    Slot* first_tombstone = nullptr;
    for (size_t i = h & mask;; i = (i + 1) & mask) {
      Slot& s = slots_[i];
      if (s.state == Slot::kEmpty) return first_tombstone ? first_tombstone : &s;
      if (s.state == Slot::kTombstone) {
        if (first_tombstone == nullptr) first_tombstone = &s;
      } else if (s.hash == h && s.key.size() == klen &&
                 std::memcmp(s.key.data(), key, klen) == 0) {
        return &s;
      }
    }
  }

  void MaybeGrow() {
    if ((size_ + tombstones_ + 1) * 10 < slots_.size() * 7) return;
    // Tombstone-dominated tables rehash in place; capacity doubles only when the
    // live load is genuinely high, so churn on a bounded working set stays bounded.
    const size_t new_cap =
        (size_ * 10 >= slots_.size() * 4) ? slots_.size() * 2 : slots_.size();
    std::vector<Slot> old;
    old.swap(slots_);
    slots_.assign(new_cap, Slot());
    size_ = 0;
    tombstones_ = 0;
    for (Slot& s : old) {
      if (s.state == Slot::kUsed) {
        MoveIn(std::move(s));
      }
    }
  }

  void MoveIn(Slot&& s) {
    const size_t mask = slots_.size() - 1;
    for (size_t i = s.hash & mask;; i = (i + 1) & mask) {
      if (slots_[i].state != Slot::kUsed) {
        slots_[i] = std::move(s);
        ++size_;
        return;
      }
    }
  }

  std::vector<Slot> slots_;
  size_t size_ = 0;
  size_t tombstones_ = 0;
};

struct Iter {
  const Store* store;
  size_t pos = 0;
};

}  // namespace

extern "C" {

void* surge_store_new() { return new Store(); }

void surge_store_free(void* h) { delete static_cast<Store*>(h); }

void surge_store_put(void* h, const char* key, size_t klen, const char* val,
                     size_t vlen) {
  static_cast<Store*>(h)->Put(key, klen, val, vlen);
}

// Returned pointer is valid until the next mutating call (the Python side copies
// immediately via ctypes.string_at).
const char* surge_store_get(void* h, const char* key, size_t klen, size_t* out_len) {
  const std::string* v = static_cast<Store*>(h)->Get(key, klen);
  if (v == nullptr) {
    *out_len = 0;
    return nullptr;
  }
  *out_len = v->size();
  return v->data();
}

void surge_store_delete(void* h, const char* key, size_t klen) {
  static_cast<Store*>(h)->Delete(key, klen);
}

size_t surge_store_size(void* h) { return static_cast<Store*>(h)->Size(); }

void surge_store_clear(void* h) { static_cast<Store*>(h)->Clear(); }

void* surge_store_iter_new(void* h) {
  return new Iter{static_cast<Store*>(h), 0};
}

int surge_store_iter_next(void* it_h, const char** key, size_t* klen,
                          const char** val, size_t* vlen) {
  Iter* it = static_cast<Iter*>(it_h);
  const auto& slots = it->store->slots();
  while (it->pos < slots.size()) {
    const Slot& s = slots[it->pos++];
    if (s.state == Slot::kUsed) {
      *key = s.key.data();
      *klen = s.key.size();
      *val = s.value.data();
      *vlen = s.value.size();
      return 1;
    }
  }
  return 0;
}

void surge_store_iter_free(void* it_h) { delete static_cast<Iter*>(it_h); }

}  // extern "C"
