// Native broker hot path: Transact batch decode, in-order/dedup gate kernel,
// and WAL journal-line formatting in one C++ call off the GIL.
//
// The reference keeps its broker hot path in compiled code (Kafka's log append
// and RocksDB's native store, PAPER.md §2.9); this file is the first-party
// equivalent for surge_tpu's broker: the per-record work of a commit — record
// framing, SLZ block compression, CRC, base64 WAL embedding and the JSON
// journal line — happens in ONE ctypes call instead of several Python passes
// per record. Compiled together with segment.cc into libsurge_segment_txn
// (csrc/build.sh), so block bytes are identical-by-construction with the
// Python segment codec.
//
// Byte-identity contract (enforced by tests/test_native_gate.py): for the same
// records, `surge_txn_format` must produce EXACTLY the bytes of
// surge_tpu.log.file._append_locked's Python path — segment.encode_block per
// contiguous run, then `json.dumps({"parts": [...], "blk": [...]}) + "\n"`
// with CPython's default separators and ensure_ascii escaping. Every decision
// of `surge_txn_decide` must equal native_gate._py_decide. Change either side
// only in lockstep.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <string>
#include <utility>
#include <vector>

#include <errno.h>
#include <unistd.h>

// from segment.cc (compiled into the same shared object)
extern "C" {
size_t surge_lz_bound(size_t n);
size_t surge_lz_compress(const uint8_t* src, size_t n, uint8_t* dst,
                         size_t dst_cap);
uint32_t surge_crc32(const uint8_t* src, size_t n);
}

namespace {

// -- protobuf wire primitives (TxnRequest/RecordMsg field numbers are pinned
// by proto/log_service.proto; the regen tool never renumbers) ----------------

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;
};

uint64_t get_varint(Cursor& c) {
  uint64_t v = 0;
  int shift = 0;
  while (c.p < c.end && shift < 64) {
    uint8_t b = *c.p++;
    v |= static_cast<uint64_t>(b & 0x7F) << shift;
    if (!(b & 0x80)) return v;
    shift += 7;
  }
  c.ok = false;
  return 0;
}

bool get_len(Cursor& c, const uint8_t** out, size_t* n) {
  uint64_t len = get_varint(c);
  if (!c.ok || c.p + len > c.end) {
    c.ok = false;
    return false;
  }
  *out = c.p;
  *n = static_cast<size_t>(len);
  c.p += len;
  return true;
}

void skip_field(Cursor& c, uint32_t wire_type) {
  switch (wire_type) {
    case 0:
      get_varint(c);
      break;
    case 1:
      if (c.p + 8 > c.end) c.ok = false; else c.p += 8;
      break;
    case 2: {
      const uint8_t* d;
      size_t n;
      get_len(c, &d, &n);
      break;
    }
    case 5:
      if (c.p + 4 > c.end) c.ok = false; else c.p += 4;
      break;
    default:
      c.ok = false;
  }
}

// -- batch model --------------------------------------------------------------

struct Rec {
  const uint8_t* key = nullptr;
  size_t key_len = 0;
  bool has_key = false;
  const uint8_t* value = nullptr;
  size_t value_len = 0;
  bool has_value = false;
  std::vector<std::pair<std::pair<const uint8_t*, size_t>,
                        std::pair<const uint8_t*, size_t>>> headers;
  int32_t group = -1;
  // verbatim (replica-ingest) batches carry leader-assigned positions; the
  // assign path leaves these untouched and stamps offsets/timestamp at
  // format time instead
  int64_t offset = -1;
  double ts = 0.0;
};

struct GroupOut {
  int64_t block_off = 0;
  int64_t block_len = 0;
  int64_t new_pos = 0;
  int32_t embedded = 0;
};

struct Batch {
  std::string buf;  // owned copy of the input bytes; Rec fields point into it
  std::vector<Rec> recs;
  std::vector<std::string> group_topics;
  std::vector<int32_t> group_parts;
  std::vector<int64_t> group_bases;  // verbatim: leader-assigned run base
  std::vector<std::vector<uint32_t>> group_members;  // arrival order per group
  uint64_t token = 0;
  uint64_t seq = 0;
  int32_t op = -1;  // 0 commit | 1 abort | 2 send_immediate | -1 other
  std::vector<int32_t> rec_groups;
  // format outputs
  std::string line;
  std::string blocks;
  std::vector<GroupOut> gout;
  std::vector<int64_t> offsets;
};

int32_t group_of(Batch* b, const uint8_t* topic, size_t topic_len,
                 int32_t partition,
                 std::map<std::pair<std::string, int32_t>, int32_t>& idx) {
  std::string t(reinterpret_cast<const char*>(topic), topic_len);
  auto key = std::make_pair(std::move(t), partition);
  auto it = idx.find(key);
  if (it != idx.end()) return it->second;
  int32_t g = static_cast<int32_t>(b->group_topics.size());
  b->group_topics.push_back(key.first);
  b->group_parts.push_back(partition);
  b->group_members.emplace_back();
  idx.emplace(std::move(key), g);
  return g;
}

bool parse_record(const uint8_t* data, size_t n, Rec* rec,
                  const uint8_t** topic, size_t* topic_len,
                  int32_t* partition) {
  Cursor c{data, data + n};
  *topic = nullptr;
  *topic_len = 0;
  *partition = 0;
  while (c.p < c.end && c.ok) {
    uint64_t tag = get_varint(c);
    if (!c.ok) return false;
    uint32_t field = static_cast<uint32_t>(tag >> 3);
    uint32_t wt = static_cast<uint32_t>(tag & 7);
    switch (field) {
      case 1:  // topic
        if (wt != 2 || !get_len(c, topic, topic_len)) return false;
        break;
      case 2:  // has_key
        if (wt != 0) return false;
        rec->has_key = get_varint(c) != 0;
        break;
      case 3:  // key
        if (wt != 2 || !get_len(c, &rec->key, &rec->key_len)) return false;
        break;
      case 4:  // has_value
        if (wt != 0) return false;
        rec->has_value = get_varint(c) != 0;
        break;
      case 5:  // value
        if (wt != 2 || !get_len(c, &rec->value, &rec->value_len)) return false;
        break;
      case 6:  // partition
        if (wt != 0) return false;
        *partition = static_cast<int32_t>(get_varint(c));
        break;
      case 7: {  // headers map entry
        if (wt != 2) return false;
        const uint8_t* ent;
        size_t ent_n;
        if (!get_len(c, &ent, &ent_n)) return false;
        Cursor hc{ent, ent + ent_n};
        const uint8_t* hk = nullptr;
        size_t hk_n = 0;
        const uint8_t* hv = nullptr;
        size_t hv_n = 0;
        while (hc.p < hc.end && hc.ok) {
          uint64_t htag = get_varint(hc);
          if (!hc.ok) return false;
          uint32_t hf = static_cast<uint32_t>(htag >> 3);
          uint32_t hwt = static_cast<uint32_t>(htag & 7);
          if (hf == 1 && hwt == 2) {
            if (!get_len(hc, &hk, &hk_n)) return false;
          } else if (hf == 2 && hwt == 2) {
            if (!get_len(hc, &hv, &hv_n)) return false;
          } else {
            skip_field(hc, hwt);
            if (!hc.ok) return false;
          }
        }
        // proto3 omits default (empty) map keys/values: absent = empty.
        // Map semantics: a duplicate key's LAST entry wins (protobuf merges
        // map entries that way) — keep one header per key, like the Python
        // side's dict.
        static const uint8_t kEmpty = 0;
        const uint8_t* kp = hk ? hk : &kEmpty;
        bool replaced = false;
        for (auto& existing : rec->headers) {
          if (existing.first.second == hk_n &&
              std::memcmp(existing.first.first, kp, hk_n) == 0) {
            existing.second = {hv ? hv : &kEmpty, hv_n};
            replaced = true;
            break;
          }
        }
        if (!replaced) {
          rec->headers.push_back({{kp, hk_n}, {hv ? hv : &kEmpty, hv_n}});
        }
        break;
      }
      case 8:  // offset (ignored: the assign path numbers records itself)
      case 9:  // timestamp (ignored: the append stamps the batch)
        skip_field(c, wt);
        if (!c.ok) return false;
        break;
      default:
        skip_field(c, wt);
        if (!c.ok) return false;
    }
  }
  return c.ok;
}

// -- record framing (the exact layout of segment.encode_records) -------------

void put_uvarint(std::string& out, uint64_t n) {
  while (n >= 0x80) {
    out.push_back(static_cast<char>((n & 0x7F) | 0x80));
    n >>= 7;
  }
  out.push_back(static_cast<char>(n));
}

void frame_record(std::string& out, const Rec& r, double timestamp) {
  uint8_t flags = (r.has_key ? 1 : 0) | (r.has_value ? 0 : 2);
  out.push_back(static_cast<char>(flags));
  if (r.has_key) {
    put_uvarint(out, r.key_len);
    out.append(reinterpret_cast<const char*>(r.key), r.key_len);
  }
  if (r.has_value) {
    put_uvarint(out, r.value_len);
    out.append(reinterpret_cast<const char*>(r.value), r.value_len);
  }
  put_uvarint(out, r.headers.size());
  // headers in sorted key order — the canonical framing (see
  // segment.encode_records): protobuf map iteration/wire orders are
  // backend-dependent, so byte-identity across the native/Python paths
  // demands one canonical order. UTF-8 bytewise == codepoint order.
  auto headers = r.headers;
  std::sort(headers.begin(), headers.end(),
            [](const auto& a, const auto& b) {
              int c = std::memcmp(
                  a.first.first, b.first.first,
                  std::min(a.first.second, b.first.second));
              if (c != 0) return c < 0;
              return a.first.second < b.first.second;
            });
  for (const auto& h : headers) {
    put_uvarint(out, h.first.second);
    out.append(reinterpret_cast<const char*>(h.first.first), h.first.second);
    put_uvarint(out, h.second.second);
    out.append(reinterpret_cast<const char*>(h.second.first), h.second.second);
  }
  char ts[8];
  std::memcpy(ts, &timestamp, 8);  // IEEE-754 little-endian, like struct "<d"
  out.append(ts, 8);
}

// block header struct "<4sB3xQIIII" (segment.py _HEADER)
void put_block_header(std::string& out, uint8_t codec, uint64_t base,
                      uint32_t count, uint32_t unlen, uint32_t plen,
                      uint32_t crc) {
  out.append("SSEG", 4);
  out.push_back(static_cast<char>(codec));
  out.append(3, '\0');
  char tmp[8];
  std::memcpy(tmp, &base, 8);
  out.append(tmp, 8);
  std::memcpy(tmp, &count, 4);
  out.append(tmp, 4);
  std::memcpy(tmp, &unlen, 4);
  out.append(tmp, 4);
  std::memcpy(tmp, &plen, 4);
  out.append(tmp, 4);
  std::memcpy(tmp, &crc, 4);
  out.append(tmp, 4);
}

// -- json helpers (CPython json.dumps default formatting) --------------------

void json_escape_utf8(std::string& out, const std::string& s) {
  static const char* hex = "0123456789abcdef";
  out.push_back('"');
  size_t i = 0;
  const size_t n = s.size();
  while (i < n) {
    unsigned char b = static_cast<unsigned char>(s[i]);
    if (b == '"' || b == '\\') {
      out.push_back('\\');
      out.push_back(static_cast<char>(b));
      ++i;
    } else if (b >= 0x20 && b < 0x7F) {
      out.push_back(static_cast<char>(b));
      ++i;
    } else if (b < 0x20) {
      switch (b) {
        case '\b': out += "\\b"; break;
        case '\t': out += "\\t"; break;
        case '\n': out += "\\n"; break;
        case '\f': out += "\\f"; break;
        case '\r': out += "\\r"; break;
        default:
          out += "\\u00";
          out.push_back(hex[b >> 4]);
          out.push_back(hex[b & 0xF]);
      }
      ++i;
    } else {
      // 0x7F (DEL; CPython json escapes every byte outside 0x20..0x7E)
      // or a non-ASCII UTF-8 sequence: emit the ensure_ascii escape
      uint32_t cp = 0;
      int extra = 0;
      if (b < 0x80) { cp = b; }
      else if ((b & 0xE0) == 0xC0) { cp = b & 0x1F; extra = 1; }
      else if ((b & 0xF0) == 0xE0) { cp = b & 0x0F; extra = 2; }
      else if ((b & 0xF8) == 0xF0) { cp = b & 0x07; extra = 3; }
      else { cp = 0xFFFD; }
      if (extra > 0 && i + extra < n) {
        for (int k = 1; k <= extra; ++k)
          cp = (cp << 6) | (static_cast<unsigned char>(s[i + k]) & 0x3F);
        i += extra + 1;
      } else if (extra > 0) {
        cp = 0xFFFD;
        i = n;
      } else {
        ++i;
      }
      auto put4 = [&](uint32_t u) {
        out += "\\u";
        out.push_back(hex[(u >> 12) & 0xF]);
        out.push_back(hex[(u >> 8) & 0xF]);
        out.push_back(hex[(u >> 4) & 0xF]);
        out.push_back(hex[u & 0xF]);
      };
      if (cp > 0xFFFF) {
        cp -= 0x10000;
        put4(0xD800 + (cp >> 10));
        put4(0xDC00 + (cp & 0x3FF));
      } else {
        put4(cp);
      }
    }
  }
  out.push_back('"');
}

void json_int(std::string& out, int64_t v) {
  char tmp[24];
  std::snprintf(tmp, sizeof(tmp), "%lld", static_cast<long long>(v));
  out += tmp;
}

// -- base64 (standard alphabet, padded — matches base64.b64encode) -----------

void b64_append(std::string& out, const uint8_t* src, size_t n) {
  static const char* tbl =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  size_t i = 0;
  for (; i + 3 <= n; i += 3) {
    uint32_t v = (src[i] << 16) | (src[i + 1] << 8) | src[i + 2];
    out.push_back(tbl[(v >> 18) & 63]);
    out.push_back(tbl[(v >> 12) & 63]);
    out.push_back(tbl[(v >> 6) & 63]);
    out.push_back(tbl[v & 63]);
  }
  if (i + 1 == n) {
    uint32_t v = src[i] << 16;
    out.push_back(tbl[(v >> 18) & 63]);
    out.push_back(tbl[(v >> 12) & 63]);
    out += "==";
  } else if (i + 2 == n) {
    uint32_t v = (src[i] << 16) | (src[i + 1] << 8);
    out.push_back(tbl[(v >> 18) & 63]);
    out.push_back(tbl[(v >> 12) & 63]);
    out.push_back(tbl[(v >> 6) & 63]);
    out.push_back('=');
  }
}

}  // namespace

extern "C" {

// Parse a serialized TxnRequest (proto/log_service.proto field numbers) into a
// batch handle: records decoded, grouped by (topic, partition) in
// first-occurrence order. Returns NULL on malformed input (caller falls back
// to the Python path).
void* surge_txn_parse_request(const uint8_t* data, size_t n) {
  Batch* b = new Batch();
  b->buf.assign(reinterpret_cast<const char*>(data), n);
  const uint8_t* base = reinterpret_cast<const uint8_t*>(b->buf.data());
  Cursor c{base, base + n};
  std::map<std::pair<std::string, int32_t>, int32_t> gidx;
  while (c.p < c.end && c.ok) {
    uint64_t tag = get_varint(c);
    if (!c.ok) break;
    uint32_t field = static_cast<uint32_t>(tag >> 3);
    uint32_t wt = static_cast<uint32_t>(tag & 7);
    if (field == 1 && wt == 0) {
      b->token = get_varint(c);
    } else if (field == 2 && wt == 2) {
      const uint8_t* op;
      size_t op_n;
      if (!get_len(c, &op, &op_n)) break;
      std::string s(reinterpret_cast<const char*>(op), op_n);
      b->op = (s == "commit") ? 0 : (s == "abort") ? 1
              : (s == "send_immediate") ? 2 : -1;
    } else if (field == 3 && wt == 2) {
      const uint8_t* rec_data;
      size_t rec_n;
      if (!get_len(c, &rec_data, &rec_n)) break;
      Rec rec;
      const uint8_t* topic;
      size_t topic_len;
      int32_t partition;
      if (!parse_record(rec_data, rec_n, &rec, &topic, &topic_len,
                        &partition)) {
        c.ok = false;
        break;
      }
      rec.group = group_of(b, topic ? topic : reinterpret_cast<const uint8_t*>(""),
                           topic_len, partition, gidx);
      b->group_members[rec.group].push_back(
          static_cast<uint32_t>(b->recs.size()));
      b->rec_groups.push_back(rec.group);
      b->recs.push_back(std::move(rec));
    } else if (field == 4 && wt == 0) {
      b->seq = get_varint(c);
    } else {
      skip_field(c, wt);
    }
  }
  if (!c.ok) {
    delete b;
    return nullptr;
  }
  return b;
}

// Parse a packed record batch (the in-process path: Python packs LogRecords in
// ONE pass; see native_gate.pack_records). meta rows per record:
//   [topic_idx, partition, flags, klen, vlen, nh, (hklen, hvlen) * nh]
// flags bit0 = has_key, bit1 = tombstone. blob = key|value|hk|hv bytes
// back-to-back in meta order; topics = topic bytes back-to-back, one entry per
// distinct topic, lengths in topic_lens.
void* surge_txn_parse_packed(const int64_t* meta, size_t meta_len,
                             const uint8_t* blob, size_t blob_len,
                             const uint8_t* topics, const int64_t* topic_lens,
                             size_t ntopics) {
  Batch* b = new Batch();
  b->buf.assign(reinterpret_cast<const char*>(blob), blob_len);
  const uint8_t* bb = reinterpret_cast<const uint8_t*>(b->buf.data());
  std::vector<std::string> topic_names(ntopics);
  {
    size_t off = 0;
    for (size_t i = 0; i < ntopics; ++i) {
      topic_names[i].assign(reinterpret_cast<const char*>(topics) + off,
                            static_cast<size_t>(topic_lens[i]));
      off += static_cast<size_t>(topic_lens[i]);
    }
  }
  std::map<std::pair<std::string, int32_t>, int32_t> gidx;
  size_t mi = 0;
  size_t bo = 0;
  bool ok = true;
  while (mi < meta_len) {
    if (mi + 6 > meta_len) { ok = false; break; }
    int64_t topic_idx = meta[mi];
    int32_t partition = static_cast<int32_t>(meta[mi + 1]);
    int64_t flags = meta[mi + 2];
    int64_t klen = meta[mi + 3];
    int64_t vlen = meta[mi + 4];
    int64_t nh = meta[mi + 5];
    mi += 6;
    if (topic_idx < 0 || static_cast<size_t>(topic_idx) >= ntopics
        || klen < 0 || vlen < 0 || nh < 0
        || mi + 2 * static_cast<size_t>(nh) > meta_len) { ok = false; break; }
    Rec rec;
    rec.has_key = (flags & 1) != 0;
    rec.has_value = (flags & 2) == 0;
    if (rec.has_key) {
      if (bo + klen > blob_len) { ok = false; break; }
      rec.key = bb + bo;
      rec.key_len = static_cast<size_t>(klen);
      bo += static_cast<size_t>(klen);
    }
    if (rec.has_value) {
      if (bo + vlen > blob_len) { ok = false; break; }
      rec.value = bb + bo;
      rec.value_len = static_cast<size_t>(vlen);
      bo += static_cast<size_t>(vlen);
    }
    for (int64_t h = 0; h < nh; ++h) {
      int64_t hk = meta[mi];
      int64_t hv = meta[mi + 1];
      mi += 2;
      if (hk < 0 || hv < 0 || bo + hk + hv > blob_len) { ok = false; break; }
      const uint8_t* kp = bb + bo;
      bo += static_cast<size_t>(hk);
      const uint8_t* vp = bb + bo;
      bo += static_cast<size_t>(hv);
      rec.headers.push_back({{kp, static_cast<size_t>(hk)},
                             {vp, static_cast<size_t>(hv)}});
    }
    if (!ok) break;
    const std::string& tname = topic_names[static_cast<size_t>(topic_idx)];
    rec.group = group_of(b, reinterpret_cast<const uint8_t*>(tname.data()),
                         tname.size(), partition, gidx);
    b->group_members[rec.group].push_back(
        static_cast<uint32_t>(b->recs.size()));
    b->rec_groups.push_back(rec.group);
    b->recs.push_back(std::move(rec));
  }
  if (!ok || bo != blob_len) {
    delete b;
    return nullptr;
  }
  return b;
}

// Parse a packed VERBATIM batch (replica ingest: leader-assigned offsets and
// timestamps preserved). meta rows as surge_txn_parse_packed; offsets/ts are
// per-record arrays in meta order. Records group into CONTIGUOUS-OFFSET RUNS
// per (topic, partition) — one segment block per run, because a block's
// decode assigns base+i and must never span an offset hole (the exact
// grouping of file.py _append_locked_py's verbatim path).
void* surge_txn_parse_packed_v(const int64_t* meta, size_t meta_len,
                               const uint8_t* blob, size_t blob_len,
                               const uint8_t* topics,
                               const int64_t* topic_lens, size_t ntopics,
                               const int64_t* offsets, const double* ts) {
  Batch* b = static_cast<Batch*>(surge_txn_parse_packed(
      meta, meta_len, blob, blob_len, topics, topic_lens, ntopics));
  if (!b) return nullptr;
  // re-group into contiguous-offset runs, ordered EXACTLY like the Python
  // verbatim path: (topic, partition) buckets in first-occurrence order,
  // each bucket's runs in record order (a run splits wherever the offset
  // chain breaks). Record storage order is untouched (offsets[i]/ts[i]
  // stay aligned with arrival order).
  for (size_t i = 0; i < b->recs.size(); ++i) {
    b->recs[i].offset = offsets[i];
    b->recs[i].ts = ts[i];
  }
  // the base parse already bucketed by (topic, partition) in
  // first-occurrence order with per-bucket members in record order — split
  // each bucket into runs
  std::vector<std::string> topics_of = std::move(b->group_topics);
  std::vector<int32_t> parts_of = std::move(b->group_parts);
  std::vector<std::vector<uint32_t>> buckets = std::move(b->group_members);
  b->group_topics.clear();
  b->group_parts.clear();
  b->group_bases.clear();
  b->group_members.clear();
  b->rec_groups.assign(b->recs.size(), -1);
  for (size_t t = 0; t < buckets.size(); ++t) {
    int32_t g = -1;
    int64_t next = 0;
    for (uint32_t ri : buckets[t]) {
      Rec& rec = b->recs[ri];
      if (g < 0 || rec.offset != next) {
        g = static_cast<int32_t>(b->group_topics.size());
        b->group_topics.push_back(topics_of[t]);
        b->group_parts.push_back(parts_of[t]);
        b->group_bases.push_back(rec.offset);
        b->group_members.emplace_back();
      }
      next = rec.offset + 1;
      rec.group = g;
      b->group_members[static_cast<size_t>(g)].push_back(ri);
      b->rec_groups[ri] = g;
    }
  }
  return b;
}

int64_t surge_txn_group_base(void* h, int64_t g) {
  Batch* b = static_cast<Batch*>(h);
  if (g < 0 || static_cast<size_t>(g) >= b->group_bases.size()) return -1;
  return b->group_bases[static_cast<size_t>(g)];
}

void surge_txn_free(void* h) { delete static_cast<Batch*>(h); }

int64_t surge_txn_nrecords(void* h) {
  return static_cast<int64_t>(static_cast<Batch*>(h)->recs.size());
}

uint64_t surge_txn_seq(void* h) { return static_cast<Batch*>(h)->seq; }

uint64_t surge_txn_token(void* h) { return static_cast<Batch*>(h)->token; }

int32_t surge_txn_op(void* h) { return static_cast<Batch*>(h)->op; }

int64_t surge_txn_ngroups(void* h) {
  return static_cast<int64_t>(static_cast<Batch*>(h)->group_topics.size());
}

const char* surge_txn_group_meta(void* h, int64_t g, int64_t* topic_len,
                                 int32_t* partition, int64_t* count) {
  Batch* b = static_cast<Batch*>(h);
  if (g < 0 || static_cast<size_t>(g) >= b->group_topics.size())
    return nullptr;
  const std::string& t = b->group_topics[static_cast<size_t>(g)];
  *topic_len = static_cast<int64_t>(t.size());
  *partition = b->group_parts[static_cast<size_t>(g)];
  *count = static_cast<int64_t>(b->group_members[static_cast<size_t>(g)].size());
  return t.data();
}

const int32_t* surge_txn_rec_groups(void* h, size_t* n) {
  Batch* b = static_cast<Batch*>(h);
  *n = b->rec_groups.size();
  return b->rec_groups.data();
}

// Format the whole transaction: one segment block per group (the assign path
// is always a single contiguous run per partition), compressed + CRC'd exactly
// like segment.encode_block, plus the journal line
// `{"parts": [[topic, p, base, count, new_pos], ...], "blk": [b64|null, ...]}\n`
// with blocks <= embed_max riding the line base64-embedded (the WAL fast
// path). bases/pos0 are per group (the caller reads them under the log lock).
// Returns 0 on success.
static int32_t format_impl(Batch* b, const int64_t* bases,
                           const int64_t* pos0, double timestamp,
                           bool per_rec_ts, int64_t embed_max) {
  const size_t ngroups = b->group_topics.size();
  b->blocks.clear();
  b->gout.assign(ngroups, GroupOut());
  b->offsets.assign(b->recs.size(), 0);
  std::string payload;
  std::string parts_json = "{\"parts\": [";
  std::string blk_json = "\"blk\": [";
  std::vector<uint8_t> comp;
  // verbatim batches can hold SEVERAL runs of one (topic, partition): each
  // later run's file position chains off the previous run's new_pos, like
  // the Python path's sequential `pos = new_pos` walk
  std::map<std::pair<std::string, int32_t>, int64_t> tp_pos;
  for (size_t g = 0; g < ngroups; ++g) {
    const auto& members = b->group_members[g];
    payload.clear();
    for (size_t i = 0; i < members.size(); ++i) {
      b->offsets[members[i]] = bases[g] + static_cast<int64_t>(i);
      const Rec& r = b->recs[members[i]];
      frame_record(payload, r, per_rec_ts ? r.ts : timestamp);
    }
    // compression decision identical to segment.slz_compress: use the
    // compressed form only when it is strictly smaller
    const uint8_t* pl = reinterpret_cast<const uint8_t*>(payload.data());
    size_t cap = surge_lz_bound(payload.size());
    comp.resize(cap);
    size_t cn = payload.empty()
        ? 0 : surge_lz_compress(pl, payload.size(), comp.data(), cap);
    uint8_t codec = 0;
    const uint8_t* stored = pl;
    size_t stored_n = payload.size();
    if (cn != 0 && cn < payload.size()) {
      codec = 1;
      stored = comp.data();
      stored_n = cn;
    }
    uint32_t crc = surge_crc32(stored, stored_n);
    GroupOut& out = b->gout[g];
    out.block_off = static_cast<int64_t>(b->blocks.size());
    put_block_header(b->blocks, codec, static_cast<uint64_t>(bases[g]),
                     static_cast<uint32_t>(members.size()),
                     static_cast<uint32_t>(payload.size()),
                     static_cast<uint32_t>(stored_n), crc);
    b->blocks.append(reinterpret_cast<const char*>(stored), stored_n);
    out.block_len = static_cast<int64_t>(b->blocks.size()) - out.block_off;
    int64_t p0 = pos0[g];
    if (per_rec_ts) {
      auto key = std::make_pair(b->group_topics[g], b->group_parts[g]);
      auto it = tp_pos.find(key);
      if (it != tp_pos.end()) p0 = it->second;
      tp_pos[key] = p0 + out.block_len;
    }
    out.new_pos = p0 + out.block_len;
    out.embedded = out.block_len <= embed_max ? 1 : 0;
    if (g) {
      parts_json += ", ";
      blk_json += ", ";
    }
    parts_json += "[";
    json_escape_utf8(parts_json, b->group_topics[g]);
    parts_json += ", ";
    json_int(parts_json, b->group_parts[g]);
    parts_json += ", ";
    json_int(parts_json, bases[g]);
    parts_json += ", ";
    json_int(parts_json, static_cast<int64_t>(members.size()));
    parts_json += ", ";
    json_int(parts_json, out.new_pos);
    parts_json += "]";
    if (out.embedded) {
      blk_json.push_back('"');
      b64_append(blk_json,
                 reinterpret_cast<const uint8_t*>(b->blocks.data())
                     + out.block_off,
                 static_cast<size_t>(out.block_len));
      blk_json.push_back('"');
    } else {
      blk_json += "null";
    }
  }
  b->line.clear();
  b->line.reserve(parts_json.size() + blk_json.size() + 8);
  b->line += parts_json;
  b->line += "], ";
  b->line += blk_json;
  b->line += "]}\n";
  return 0;
}

int32_t surge_txn_format(void* h, const int64_t* bases, const int64_t* pos0,
                         double timestamp, int64_t embed_max) {
  return format_impl(static_cast<Batch*>(h), bases, pos0, timestamp,
                    /*per_rec_ts=*/false, embed_max);
}

// Verbatim twin of surge_txn_format for replica ingest: block bases come
// from the leader-assigned run bases captured at parse, and every record
// frames with ITS OWN timestamp — a replica's segment files converge
// byte-identically with the leader's (file.py _append_locked_py verbatim).
int32_t surge_txn_format_verbatim(void* h, const int64_t* pos0,
                                  int64_t embed_max) {
  Batch* b = static_cast<Batch*>(h);
  return format_impl(b, b->group_bases.data(), pos0, 0.0,
                    /*per_rec_ts=*/true, embed_max);
}

const uint8_t* surge_txn_line(void* h, size_t* n) {
  Batch* b = static_cast<Batch*>(h);
  *n = b->line.size();
  return reinterpret_cast<const uint8_t*>(b->line.data());
}

const uint8_t* surge_txn_blocks(void* h, size_t* n) {
  Batch* b = static_cast<Batch*>(h);
  *n = b->blocks.size();
  return reinterpret_cast<const uint8_t*>(b->blocks.data());
}

int32_t surge_txn_group_out(void* h, int64_t g, int64_t* block_off,
                            int64_t* block_len, int32_t* embedded,
                            int64_t* new_pos) {
  Batch* b = static_cast<Batch*>(h);
  if (g < 0 || static_cast<size_t>(g) >= b->gout.size()) return -1;
  const GroupOut& out = b->gout[static_cast<size_t>(g)];
  *block_off = out.block_off;
  *block_len = out.block_len;
  *embedded = out.embedded;
  *new_pos = out.new_pos;
  return 0;
}

const int64_t* surge_txn_offsets(void* h, size_t* n) {
  Batch* b = static_cast<Batch*>(h);
  *n = b->offsets.size();
  return b->offsets.data();
}

// The in-order/dedup gate decision kernel — the scalar half of the broker's
// per-producer Transact gate (window/alias/pending bookkeeping stays in
// Python, which owns that state). Must stay in lockstep with
// native_gate._py_decide:
//   0 ACCEPT        apply now (seq == applied+1, or unsequenced)
//   1 REPLAY        seq <= last acked: answer from the dedup window
//   2 MAYBE_REOPEN  first seq of a reopened producer at last+1: absorption
//                   candidate (payload match decides, in Python)
//   3 WAIT          a predecessor has not applied: hold at the in-order gate
//   4 FINALIZING    applied but not acked: ack bookkeeping is in flight
int32_t surge_txn_decide(uint64_t seq, uint64_t last_seq, uint64_t applied_seq,
                         int32_t fresh) {
  if (seq == 0) return 0;
  if (seq <= last_seq) return 1;
  if (fresh && seq == last_seq + 1 && last_seq != 0 && seq > applied_seq)
    return 2;
  if (seq > applied_seq + 1) return 3;
  if (seq <= applied_seq) return 4;
  return 0;
}

// One write(+fsync) for a whole group-commit round's journal buffers: the
// group-sync worker hands the round's concatenated lines here, paying a single
// GIL-free call instead of a Python write/flush per commit. n == 0 with
// do_fsync performs a bare fsync (the off-lock half of the round).
// Returns bytes written, or -errno.
int64_t surge_wal_append(int32_t fd, const uint8_t* buf, size_t n,
                         int32_t do_fsync) {
  size_t done = 0;
  while (done < n) {
    ssize_t w = ::write(fd, buf + done, n - done);
    if (w < 0) {
      if (errno == EINTR) continue;
      return -static_cast<int64_t>(errno);
    }
    done += static_cast<size_t>(w);
  }
  if (do_fsync) {
    if (::fsync(fd) != 0) return -static_cast<int64_t>(errno);
  }
  return static_cast<int64_t>(done);
}

// Batch record-index decode: walk an (uncompressed) segment block payload and
// emit one fixed-width index row per record —
//   [flags, key_off, key_len, val_off, val_len, hdr_off, hdr_cnt]
// plus the timestamp array, so the Python side builds records with slices
// instead of a per-byte uvarint walk (the resident plane's refresh loop and
// every FileLog read ride this). Returns bytes consumed, or -1 on a
// malformed/truncated payload (caller falls back to the Python decoder).
int64_t surge_seg_index(const uint8_t* payload, size_t n, int64_t count,
                        int64_t* out_rows, double* out_ts) {
  size_t pos = 0;
  auto uvarint = [&](uint64_t* v) -> bool {
    *v = 0;
    int shift = 0;
    while (pos < n && shift < 64) {
      uint8_t b = payload[pos++];
      *v |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) return true;
      shift += 7;
    }
    return false;
  };
  for (int64_t i = 0; i < count; ++i) {
    if (pos >= n) return -1;
    uint8_t flags = payload[pos++];
    int64_t* row = out_rows + i * 7;
    row[0] = flags;
    row[1] = row[2] = row[3] = row[4] = 0;
    if (flags & 1) {
      uint64_t klen;
      if (!uvarint(&klen) || pos + klen > n) return -1;
      row[1] = static_cast<int64_t>(pos);
      row[2] = static_cast<int64_t>(klen);
      pos += klen;
    }
    if (!(flags & 2)) {
      uint64_t vlen;
      if (!uvarint(&vlen) || pos + vlen > n) return -1;
      row[3] = static_cast<int64_t>(pos);
      row[4] = static_cast<int64_t>(vlen);
      pos += vlen;
    }
    uint64_t nh;
    if (!uvarint(&nh)) return -1;
    row[5] = static_cast<int64_t>(pos);
    row[6] = static_cast<int64_t>(nh);
    for (uint64_t hdr = 0; hdr < nh; ++hdr) {
      uint64_t len;
      if (!uvarint(&len) || pos + len > n) return -1;
      pos += len;
      if (!uvarint(&len) || pos + len > n) return -1;
      pos += len;
    }
    if (pos + 8 > n) return -1;
    std::memcpy(out_ts + i, payload + pos, 8);
    pos += 8;
  }
  return static_cast<int64_t>(pos);
}

// -- reply legs: packed record-view materializer + wire reply formatter ------
//
// The read/reply hot path used to build one frozen-dataclass LogRecord (or
// one protobuf RecordMsg) per record in Python — ~2.8 us each. These two
// calls move the per-record work native: surge_reply_index walks a
// serialized reply's repeated RecordMsg field and emits fixed-width index
// rows (Python wraps them in lazy decode-on-access views over the reply
// bytes); surge_reply_format emits the serialized repeated-RecordMsg bytes
// for a packed record batch in one call (the server's Read reply rides it
// verbatim through a passthrough gRPC serializer).

// Count the top-level length-delimited occurrences of `field` in a
// serialized message (the sizing pass for surge_reply_index). -1 on
// malformed input.
int64_t surge_reply_count(const uint8_t* data, size_t n, int32_t field) {
  Cursor c{data, data + n};
  int64_t count = 0;
  while (c.p < c.end && c.ok) {
    uint64_t tag = get_varint(c);
    if (!c.ok) return -1;
    uint32_t f = static_cast<uint32_t>(tag >> 3);
    uint32_t wt = static_cast<uint32_t>(tag & 7);
    if (f == static_cast<uint32_t>(field) && wt == 2) {
      const uint8_t* d;
      size_t dn;
      if (!get_len(c, &d, &dn)) return -1;
      ++count;
    } else {
      skip_field(c, wt);
      if (!c.ok) return -1;
    }
  }
  return count;
}

// Index every RecordMsg in the top-level repeated `field` of a serialized
// reply. 12 int64s per row:
//   [flags, topic_off, topic_len, key_off, key_len, val_off, val_len,
//    partition, offset, hdr_cnt, msg_off, msg_len]
// flags bit0 = has_key, bit1 = tombstone (has_value false). Offsets are into
// the reply bytes; Python's lazy views slice on access (headers re-walk
// [msg_off, msg_off+msg_len) only when touched — hdr_cnt tells them whether
// to bother). Returns rows written, or -1 on malformed/overflowing input.
int64_t surge_reply_index(const uint8_t* data, size_t n, int32_t field,
                          int64_t* rows, size_t max_rows, double* out_ts) {
  Cursor c{data, data + n};
  int64_t count = 0;
  while (c.p < c.end && c.ok) {
    uint64_t tag = get_varint(c);
    if (!c.ok) return -1;
    uint32_t f = static_cast<uint32_t>(tag >> 3);
    uint32_t wt = static_cast<uint32_t>(tag & 7);
    if (f != static_cast<uint32_t>(field) || wt != 2) {
      skip_field(c, wt);
      if (!c.ok) return -1;
      continue;
    }
    const uint8_t* msg;
    size_t msg_n;
    if (!get_len(c, &msg, &msg_n)) return -1;
    if (static_cast<size_t>(count) >= max_rows) return -1;
    int64_t* row = rows + count * 12;
    for (int k = 0; k < 12; ++k) row[k] = 0;
    row[10] = static_cast<int64_t>(msg - data);
    row[11] = static_cast<int64_t>(msg_n);
    out_ts[count] = 0.0;
    Cursor mc{msg, msg + msg_n};
    bool has_value = false;
    while (mc.p < mc.end && mc.ok) {
      uint64_t mtag = get_varint(mc);
      if (!mc.ok) return -1;
      uint32_t mf = static_cast<uint32_t>(mtag >> 3);
      uint32_t mwt = static_cast<uint32_t>(mtag & 7);
      const uint8_t* d;
      size_t dn;
      switch (mf) {
        case 1:  // topic
          if (mwt != 2 || !get_len(mc, &d, &dn)) return -1;
          row[1] = static_cast<int64_t>(d - data);
          row[2] = static_cast<int64_t>(dn);
          break;
        case 2:  // has_key
          if (mwt != 0) return -1;
          if (get_varint(mc)) row[0] |= 1;
          break;
        case 3:  // key
          if (mwt != 2 || !get_len(mc, &d, &dn)) return -1;
          row[3] = static_cast<int64_t>(d - data);
          row[4] = static_cast<int64_t>(dn);
          break;
        case 4:  // has_value
          if (mwt != 0) return -1;
          has_value = get_varint(mc) != 0;
          break;
        case 5:  // value
          if (mwt != 2 || !get_len(mc, &d, &dn)) return -1;
          row[5] = static_cast<int64_t>(d - data);
          row[6] = static_cast<int64_t>(dn);
          break;
        case 6:  // partition
          if (mwt != 0) return -1;
          row[7] = static_cast<int64_t>(get_varint(mc));
          break;
        case 7:  // headers map entry (counted; decoded lazily in Python)
          if (mwt != 2 || !get_len(mc, &d, &dn)) return -1;
          row[9] += 1;
          break;
        case 8:  // offset
          if (mwt != 0) return -1;
          row[8] = static_cast<int64_t>(get_varint(mc));
          break;
        case 9: {  // timestamp (double, wire type 1)
          if (mwt != 1 || mc.p + 8 > mc.end) return -1;
          std::memcpy(out_ts + count, mc.p, 8);
          mc.p += 8;
          break;
        }
        default:
          skip_field(mc, mwt);
          if (!mc.ok) return -1;
      }
    }
    if (!mc.ok) return -1;
    if (!has_value) row[0] |= 2;
    ++count;
  }
  return count;
}

namespace {

void put_tag(std::string& out, uint32_t field, uint32_t wt) {
  put_uvarint(out, (static_cast<uint64_t>(field) << 3) | wt);
}

void put_len_field(std::string& out, uint32_t field, const uint8_t* d,
                   size_t n) {
  put_tag(out, field, 2);
  put_uvarint(out, n);
  out.append(reinterpret_cast<const char*>(d), n);
}

}  // namespace

// Serialize a packed record batch as the repeated RecordMsg `field` of a
// reply message, proto3-canonically: fields in number order, defaults
// skipped, headers as map entries in SORTED key order (protobuf map wire
// order is backend-dependent; one canonical order is what lets the property
// test compare bytes against the pure-Python twin). meta rows per record:
//   [topic_idx, partition, flags, klen, vlen, nh, offset, (hklen, hvlen)*nh]
// flags/blob/topics as surge_txn_parse_packed; ts per record. Returns bytes
// written into out (capacity out_cap), or -1 (malformed meta / overflow —
// callers fall back to the Python path).
int64_t surge_reply_format(const int64_t* meta, size_t meta_len,
                           const uint8_t* blob, size_t blob_len,
                           const uint8_t* topics, const int64_t* topic_lens,
                           size_t ntopics, const double* ts, int32_t field,
                           uint8_t* out, size_t out_cap) {
  std::vector<std::pair<const uint8_t*, size_t>> topic_ptrs(ntopics);
  {
    size_t off = 0;
    for (size_t i = 0; i < ntopics; ++i) {
      topic_ptrs[i] = {topics + off, static_cast<size_t>(topic_lens[i])};
      off += static_cast<size_t>(topic_lens[i]);
    }
  }
  std::string msg;
  std::string body;
  size_t mi = 0;
  size_t bo = 0;
  size_t written = 0;
  size_t rec_i = 0;
  std::vector<std::pair<std::pair<const uint8_t*, size_t>,
                        std::pair<const uint8_t*, size_t>>> hdrs;
  while (mi < meta_len) {
    if (mi + 7 > meta_len) return -1;
    int64_t topic_idx = meta[mi];
    int64_t partition = meta[mi + 1];
    int64_t flags = meta[mi + 2];
    int64_t klen = meta[mi + 3];
    int64_t vlen = meta[mi + 4];
    int64_t nh = meta[mi + 5];
    int64_t offset = meta[mi + 6];
    mi += 7;
    if (topic_idx < 0 || static_cast<size_t>(topic_idx) >= ntopics
        || klen < 0 || vlen < 0 || nh < 0
        || mi + 2 * static_cast<size_t>(nh) > meta_len) return -1;
    msg.clear();
    if (topic_ptrs[static_cast<size_t>(topic_idx)].second) {
      put_len_field(msg, 1, topic_ptrs[static_cast<size_t>(topic_idx)].first,
                    topic_ptrs[static_cast<size_t>(topic_idx)].second);
    }
    const bool has_key = (flags & 1) != 0;
    const bool tombstone = (flags & 2) != 0;
    const uint8_t* key = nullptr;
    const uint8_t* value = nullptr;
    if (has_key) {
      if (bo + static_cast<size_t>(klen) > blob_len) return -1;
      key = blob + bo;
      bo += static_cast<size_t>(klen);
      put_tag(msg, 2, 0);
      msg.push_back(1);
      if (klen) put_len_field(msg, 3, key, static_cast<size_t>(klen));
    }
    if (!tombstone) {
      if (bo + static_cast<size_t>(vlen) > blob_len) return -1;
      value = blob + bo;
      bo += static_cast<size_t>(vlen);
      put_tag(msg, 4, 0);
      msg.push_back(1);
      if (vlen) put_len_field(msg, 5, value, static_cast<size_t>(vlen));
    }
    if (partition) {
      put_tag(msg, 6, 0);
      put_uvarint(msg, static_cast<uint64_t>(partition));
    }
    if (nh) {
      hdrs.clear();
      for (int64_t hkx = 0; hkx < nh; ++hkx) {
        int64_t hk = meta[mi];
        int64_t hv = meta[mi + 1];
        mi += 2;
        if (hk < 0 || hv < 0
            || bo + static_cast<size_t>(hk + hv) > blob_len) return -1;
        const uint8_t* kp = blob + bo;
        bo += static_cast<size_t>(hk);
        const uint8_t* vp = blob + bo;
        bo += static_cast<size_t>(hv);
        hdrs.push_back({{kp, static_cast<size_t>(hk)},
                        {vp, static_cast<size_t>(hv)}});
      }
      std::sort(hdrs.begin(), hdrs.end(), [](const auto& a, const auto& b) {
        int c = std::memcmp(a.first.first, b.first.first,
                            std::min(a.first.second, b.first.second));
        if (c != 0) return c < 0;
        return a.first.second < b.first.second;
      });
      for (const auto& hkv : hdrs) {
        body.clear();
        if (hkv.first.second)
          put_len_field(body, 1, hkv.first.first, hkv.first.second);
        if (hkv.second.second)
          put_len_field(body, 2, hkv.second.first, hkv.second.second);
        put_tag(msg, 7, 2);
        put_uvarint(msg, body.size());
        msg += body;
      }
    }
    if (offset) {
      put_tag(msg, 8, 0);
      put_uvarint(msg, static_cast<uint64_t>(offset));
    }
    uint64_t ts_bits;
    std::memcpy(&ts_bits, ts + rec_i, 8);
    if (ts_bits) {
      put_tag(msg, 9, 1);
      char tmp[8];
      std::memcpy(tmp, ts + rec_i, 8);
      msg.append(tmp, 8);
    }
    ++rec_i;
    // frame: tag(field, len-delimited) + len + msg
    std::string hdr;
    put_tag(hdr, static_cast<uint32_t>(field), 2);
    put_uvarint(hdr, msg.size());
    if (written + hdr.size() + msg.size() > out_cap) return -1;
    std::memcpy(out + written, hdr.data(), hdr.size());
    written += hdr.size();
    std::memcpy(out + written, msg.data(), msg.size());
    written += msg.size();
  }
  if (bo != blob_len) return -1;
  return static_cast<int64_t>(written);
}

}  // extern "C"
