#!/usr/bin/env python
"""On-chip knob sweep: convert ANY live tunnel window into a persisted artifact.

Four rounds of benching bet each TPU claim on a full-scale run and produced zero
machine-readable on-chip numbers (VERDICT r4 "missing" #1).  This module inverts
that: the moment a backend initializes, it

  1. probes the link (sync latency, H2D bandwidth single vs chunked puts),
  2. runs a SMOKE-scale resident replay sweep over the prepared knobs
     (dispatch switch|select, unroll, time-chunk, tile-backend xla|pallas,
     chunked upload, streamed segments), verifying every config against the
     closed-form fold,
  3. rewrites the artifact JSON after EVERY measurement, so a tunnel drop
     mid-sweep still leaves on-chip evidence,
  4. optionally re-runs the best configs at full scale (1M/100M).

Called from bench.py's TPU replay child (artifact lands before the full-scale
attempt) and runnable standalone.  The reference benches its restore/throughput
on its real broker the same way — measured, not estimated (SURVEY.md §6).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.abspath(__file__))
ARTIFACT = os.path.join(REPO, "BENCH_ONCHIP.json")


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class Artifact:
    """Incrementally-rewritten JSON sidecar; every update is atomic."""

    def __init__(self, path: str):
        self.path = path
        self.data: dict = {"started_utc": _now(), "done": False}

    def update(self, **kv) -> None:
        self.data.update(kv)
        self.data["updated_utc"] = _now()
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.data, f, indent=1)
        os.replace(tmp, self.path)


def _probe_link(jax) -> dict:
    """Sync latency + H2D bandwidth, single put vs 16MB pieces."""
    import jax.numpy as jnp

    out: dict = {}
    # sync latency: tiny transfer + block, median of 10
    x = np.zeros((8,), dtype=np.int32)
    ts = []
    for _ in range(10):
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(x))
        ts.append(time.perf_counter() - t0)
    out["sync_ms"] = round(1000 * sorted(ts)[len(ts) // 2], 2)

    big = np.random.default_rng(0).integers(0, 255, size=(96 * 1024 * 1024,),
                                            dtype=np.uint8)
    t0 = time.perf_counter()
    d = jax.device_put(big)
    jax.block_until_ready(d)
    single = time.perf_counter() - t0
    out["h2d_single_96mb_mb_s"] = round(big.nbytes / 1e6 / single, 1)
    del d
    ch = 16 * 1024 * 1024
    t0 = time.perf_counter()
    parts = [jax.device_put(big[i:i + ch]) for i in range(0, big.nbytes, ch)]
    jax.block_until_ready(parts)
    chunked = time.perf_counter() - t0
    t0 = time.perf_counter()
    whole = jnp.concatenate(parts, axis=0)
    jax.block_until_ready(whole)
    out["h2d_chunked_16mb_mb_s"] = round(big.nbytes / 1e6 / chunked, 1)
    out["h2d_concat_s"] = round(time.perf_counter() - t0, 3)
    del parts, whole, big
    return out


def ensure_corpus_cache(cache_dir: str, num_agg: int, num_events: int,
                        seed: int) -> None:
    """Build the corpus + packed wire at ``cache_dir`` unless already cached.

    Crash-safe: the cache is only trusted when its ``complete.json`` marker —
    written LAST — exists and records the same corpus sizes; anything else
    (mid-build kill, different parameters) is wiped and rebuilt.  A poisoned
    cache would otherwise fail every subsequent tunnel attempt, which is the
    exact outcome this module exists to prevent."""
    import shutil

    from bench import make_engine, save_corpus
    from surge_tpu.replay.corpus import synth_counter_corpus

    marker = os.path.join(cache_dir, "complete.json")
    want = {"num_aggregates": num_agg, "num_events": num_events, "seed": seed}
    if os.path.exists(marker):
        try:
            with open(marker) as f:
                if json.load(f) == want:
                    return
        except (OSError, ValueError):
            pass
    shutil.rmtree(cache_dir, ignore_errors=True)
    os.makedirs(cache_dir)
    corpus = synth_counter_corpus(num_agg, num_events, seed=seed,
                                  sort_by_length=True)
    save_corpus(corpus, cache_dir)
    make_engine().pack_resident(corpus.events).save(
        os.path.join(cache_dir, "wire"))
    tmp = marker + ".tmp"
    with open(tmp, "w") as f:
        json.dump(want, f)
    os.replace(tmp, marker)


def _smoke_corpus(cache_dir: str, num_agg: int, num_events: int):
    """Build-or-load the smoke corpus + packed wire (cached across attempts)."""
    from surge_tpu.replay.engine import ResidentWire

    ensure_corpus_cache(cache_dir, num_agg, num_events, seed=43)
    expected = {
        "count": np.load(os.path.join(cache_dir, "expected_count.npy")),
        "version": np.load(os.path.join(cache_dir, "expected_version.npy")),
    }
    return ResidentWire.load(os.path.join(cache_dir, "wire")), expected


def _engine(overrides: dict, unroll: int):
    from surge_tpu.config import default_config
    from surge_tpu.models.counter import make_replay_spec
    from surge_tpu.replay.engine import ReplayEngine

    cfg = default_config().with_overrides({
        "surge.replay.batch-size": 8192,
        "surge.replay.time-chunk": 128,
        "surge.replay.resident-len-bucket": "exact",
        **overrides,
    })
    return ReplayEngine(make_replay_spec(), config=cfg, unroll=unroll)


def _run_config(wire, expected, *, dispatch="switch", unroll=1, time_chunk=128,
                tile="auto", layout="auto", batch=8192, chunk_mb=0,
                passes=3) -> dict:
    """Upload + warm + throwaway + timed passes for one knob combination."""
    cfg = {"dispatch": dispatch, "unroll": unroll, "time_chunk": time_chunk,
           "tile": tile, "layout": layout, "batch": batch,
           "chunk_mb": chunk_mb}
    try:
        eng = _engine({
            "surge.replay.time-chunk": time_chunk,
            "surge.replay.dispatch": dispatch,
            "surge.replay.tile-backend": tile,
            "surge.replay.resident-layout": layout,
            "surge.replay.batch-size": batch,
            "surge.replay.upload-chunk-mb": chunk_mb,
        }, unroll)
        t0 = time.perf_counter()
        res = eng.upload_resident(wire)
        upload_s = time.perf_counter() - t0
        eng.warm_resident(res)
        t0 = time.perf_counter()
        r = eng.replay_resident(res)
        first_s = time.perf_counter() - t0
        steady = 1e9
        for _ in range(passes):
            t0 = time.perf_counter()
            r = eng.replay_resident(res)
            steady = min(steady, time.perf_counter() - t0)
        n = wire.num_events
        ok = (np.array_equal(r.states["count"], expected["count"])
              and np.array_equal(r.states["version"], expected["version"]))
        return {**cfg, "upload_s": round(upload_s, 3),
                "first_pass_s": round(first_s, 3),
                "steady_s": round(steady, 4),
                "events_per_sec": round(n / steady),
                "pad_ratio": round(r.padded_events / n, 3),
                "verified": bool(ok)}
    except Exception as e:  # noqa: BLE001 — a failing config must not kill the sweep
        return {**cfg, "error": f"{type(e).__name__}: {str(e)[:400]}"}


def _verify_families(on_row=None) -> list:
    """Every model family + the collective programs verified ON THIS BACKEND,
    through the same resident path the flagship benches (auto knobs: dense
    layout + assoc fold where the family ships one): bank_account (f32 +
    vocab side columns, wide pull), shopping_cart (bool state), the
    three-family mixed batch, and the seqpar time-sharded program on a
    1-device mesh. Each row: family, sizes, verified, seconds. ``on_row``
    (rows -> None) fires after every row so the caller can re-bank the
    artifact incrementally — a tunnel drop mid-family keeps earlier rows."""
    import random

    import jax

    from surge_tpu.codec.tensor import encode_events_columnar
    from surge_tpu.config import Config
    from surge_tpu.engine.model import fold_events
    from surge_tpu.models import bank_account, counter, shopping_cart
    from surge_tpu.replay import ReplayEngine
    from surge_tpu.testing import (random_bank_log, random_cart_log,
                                   random_counter_log)

    rng = random.Random(17)
    rows: list = []

    def bank(row):
        rows.append(row)
        if on_row is not None:
            on_row(rows)

    def single_family(name, model, spec, logs, fields, encode=None):
        t0 = time.perf_counter()
        try:
            truth = [fold_events(model, None, log) for log in logs]
            enc_logs = ([[encode(e) for e in log] for log in logs]
                        if encode else logs)
            ev = encode_events_columnar(spec.registry, enc_logs)
            eng = ReplayEngine(spec, config=Config({
                "surge.replay.batch-size": 256,
                "surge.replay.time-chunk": 32}))
            res = eng.replay_resident(eng.prepare_resident(ev))
            ok = True
            for i, t in enumerate(truth):
                for f in fields:
                    want = getattr(t, f) if t is not None else 0
                    got = res.states[f][i]
                    if isinstance(want, float):
                        ok &= abs(float(got) - want) < 1e-4
                    else:
                        ok &= bool(got) == bool(want) if isinstance(
                            want, bool) else int(got) == int(want)
            bank({"family": name, "aggregates": len(logs),
                         "events": res.num_events, "tile": eng.tile_backend,
                         "verified": bool(ok),
                         "s": round(time.perf_counter() - t0, 1)})
        except Exception as e:  # noqa: BLE001 — record, don't kill the sweep
            bank({"family": name,
                         "error": f"{type(e).__name__}: {str(e)[:200]}"})

    vocab = bank_account.Vocab()
    single_family(
        "bank_account", bank_account.BankAccountModel(),
        bank_account.make_replay_spec(),
        [random_bank_log(rng, f"b{i}") for i in range(301)],
        fields=("balance",),
        encode=lambda e: bank_account.encode_event(vocab, e))
    single_family(
        "shopping_cart", shopping_cart.CartModel(),
        shopping_cart.make_replay_spec(),
        [random_cart_log(rng, f"c{i}") for i in range(301)],
        fields=("item_count", "total_cents", "checked_out", "version"))

    # three families in ONE batch (tagged-union columns, masked dispatch)
    t0 = time.perf_counter()
    try:
        from surge_tpu.replay.mixed import combine_replay_specs

        mixed = combine_replay_specs({
            "counter": counter.make_replay_spec(),
            "cart": shopping_cart.make_replay_spec(),
            "bank": bank_account.make_replay_spec()})
        models = {"counter": counter.CounterModel(),
                  "cart": shopping_cart.CartModel(),
                  "bank": bank_account.BankAccountModel()}
        makers = {"counter": random_counter_log, "cart": random_cart_log,
                  "bank": random_bank_log}
        tagged, truths = [], []
        for i in range(240):
            kind = ("counter", "cart", "bank")[i % 3]
            log = makers[kind](rng, f"m{i}")
            truths.append((kind, fold_events(models[kind], None, log)))
            if kind == "bank":
                log = [bank_account.encode_event(vocab, e) for e in log]
            tagged.append((kind, log))
        colev = mixed.encode_logs(tagged)
        eng = ReplayEngine(mixed.spec, config=Config({
            "surge.replay.batch-size": 64, "surge.replay.time-chunk": 8}))
        tags = [m for m, _ in tagged]
        res = eng.replay_resident(eng.prepare_resident(colev),
                                  init_carry=mixed.init_carry(tags))
        decoded = mixed.decode_states(tags, res.states)
        ok = all(
            (t is None) or
            (kind == "counter" and d.count == t.count) or
            (kind == "cart" and d.total_cents == t.total_cents) or
            (kind == "bank" and abs(d.balance - t.balance) < 1e-4)
            for (kind, t), d in zip(truths, decoded))
        bank({"family": "mixed(counter+cart+bank)", "aggregates": 240,
                     "events": res.num_events, "verified": bool(ok),
                     "s": round(time.perf_counter() - t0, 1)})
    except Exception as e:  # noqa: BLE001
        bank({"family": "mixed",
                     "error": f"{type(e).__name__}: {str(e)[:200]}"})

    # seqpar time-sharded program on a 1-device mesh of THIS backend
    t0 = time.perf_counter()
    try:
        from surge_tpu.codec.tensor import encode_events
        from surge_tpu.replay.seqpar import replay_time_sharded

        mesh = jax.sharding.Mesh(np.asarray(jax.devices()[:1]), ("data",))
        model = counter.CounterModel()
        spec = counter.make_replay_spec()
        logs = [random_counter_log(rng, f"s{i}") for i in range(24)]
        enc = encode_events(spec.registry, logs)
        events = {"type_id": enc.type_ids.T.astype(np.int32)}
        for cname, col in enc.cols.items():
            events[cname] = col.T
        out = replay_time_sharded(counter.make_associative_fold(), spec,
                                  events, mesh)
        truth = [fold_events(model, None, log) for log in logs]
        ok = all(int(out["count"][i]) == (t.count if t else 0)
                 and int(out["version"][i]) == (t.version if t else 0)
                 for i, t in enumerate(truth))
        bank({"family": "seqpar_time_sharded", "aggregates": len(logs),
                     "events": sum(len(l) for l in logs),
                     "verified": bool(ok),
                     "s": round(time.perf_counter() - t0, 1)})
    except Exception as e:  # noqa: BLE001
        bank({"family": "seqpar_time_sharded",
                     "error": f"{type(e).__name__}: {str(e)[:200]}"})
    return rows


def _run_streamed(wire, expected, segments: int) -> dict:
    cfg = {"streamed_segments": segments}
    try:
        eng = _engine({}, 1)
        eng.replay_resident_streamed(wire, segments=segments)  # warm/compile
        t0 = time.perf_counter()
        r = eng.replay_resident_streamed(wire, segments=segments)
        dt = time.perf_counter() - t0
        ok = (np.array_equal(r.states["count"], expected["count"])
              and np.array_equal(r.states["version"], expected["version"]))
        return {**cfg, "total_s": round(dt, 3),
                "events_per_sec_incl_upload": round(wire.num_events / dt),
                "verified": bool(ok)}
    except Exception as e:  # noqa: BLE001
        return {**cfg, "error": f"{type(e).__name__}: {str(e)[:400]}"}


SMOKE_CONFIGS = (
    # expected winner after the r5 redesign: dense pre-gathered tiles + the
    # assoc tree-reduction fold + u16 single-fetch pull. layout='dense' is
    # EXPLICIT on every dense-claiming row: at smoke scale (~8M padded slots)
    # the engine's 16M-slot _use_dense floor resolves 'auto' to flat, so the
    # auto rows would silently duplicate their layout='flat' twins and the
    # smoke section would never isolate dense vs flat (ADVICE r5)
    dict(layout="dense"),
    # isolate each r5 lever against the winner
    dict(tile="xla", layout="dense"),      # dense tiles, sequential scan
    dict(tile="assoc", layout="flat"),     # per-pass gather, tree fold
    dict(tile="xla", layout="flat"),       # the r4 baseline program
    # dispatch form + pallas kernel comparison on the dense layout
    dict(dispatch="select", layout="dense"),
    dict(dispatch="select", tile="pallas", layout="dense"),
    # tile geometry under assoc: pad ratio vs tile count (auto layout — the
    # geometry levers act the same either side of the dense floor)
    dict(time_chunk=64),
    dict(time_chunk=256),
    dict(batch=32768),
    # upload pipelining (the one-time cost; chunked H2D measured 25% faster)
    dict(chunk_mb=16),
)

#: _run_config's knob defaults — contender dedup keys normalize against these
#: so a smoke 'best' row spelling every knob explicitly still collides with
#: the all-auto dict() contender when they are the same config (ADVICE r5:
#: the most expensive 100M-event config must not run twice)
_RUN_CONFIG_DEFAULTS = dict(dispatch="switch", unroll=1, time_chunk=128,
                            tile="auto", layout="auto", batch=8192, chunk_mb=0)


def _device_fold_ceiling(corpus_dir: str) -> float | None:
    """Transfer-free fold slots/s on this backend (bench helper reused)."""
    try:
        from bench import _device_resident_fold_rate, load_corpus, make_engine
        corpus = load_corpus(corpus_dir)
        return round(_device_resident_fold_rate(make_engine(), corpus))
    except Exception:  # noqa: BLE001
        return None


def run_sweep(artifact_path: str = ARTIFACT, *,
              smoke_aggregates: int = 50_000, smoke_events: int = 5_000_000,
              smoke_cache: str | None = None,
              full_corpus_dir: str | None = None) -> dict:
    """The whole sweep.  Returns the best smoke config's knob dict (smoke
    rates are tunnel-latency-floored — informational, not a tuning signal;
    the ``full`` section of the artifact carries the decisive numbers)."""
    sys.path.insert(0, REPO)
    art = Artifact(artifact_path)
    try:
        import subprocess

        art.update(repo_commit=subprocess.run(
            ["git", "-C", REPO, "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10).stdout.strip())
    except Exception:  # noqa: BLE001 — provenance only
        pass

    t0 = time.perf_counter()
    import jax

    try:
        devices = jax.devices()  # may hang ~25 min, raise if the pool is down
    except Exception as exc:
        # the leftover artifact must say WHY there is no on-chip data
        art.update(claim_error=f"{type(exc).__name__}: {str(exc)[:300]}",
                   claim_s=round(time.perf_counter() - t0, 1))
        raise
    claim_s = time.perf_counter() - t0
    platform = devices[0].platform
    art.update(platform=platform, device=str(devices[0]),
               claim_s=round(claim_s, 1))

    art.update(probe=_probe_link(jax))

    cache = smoke_cache or os.environ.get("SURGE_ONCHIP_CACHE",
                                          "/tmp/corpus_smoke5m")
    t0 = time.perf_counter()
    wire, expected = _smoke_corpus(cache, smoke_aggregates, smoke_events)
    smoke: dict = {"num_aggregates": smoke_aggregates,
                   "num_events": smoke_events,
                   "corpus_s": round(time.perf_counter() - t0, 1),
                   "configs": []}
    art.update(smoke=smoke)

    for kw in SMOKE_CONFIGS:
        row = _run_config(wire, expected, **kw)
        smoke["configs"].append(row)
        art.update(smoke=smoke)
    for segments in (4, 8):
        row = _run_streamed(wire, expected, segments)
        smoke["configs"].append(row)
        art.update(smoke=smoke)

    ok_rows = [c for c in smoke["configs"]
               if c.get("verified") and "events_per_sec" in c]
    best = max(ok_rows, key=lambda c: c["events_per_sec"]) if ok_rows else {}
    smoke["best"] = best
    smoke["device_fold_slots_per_sec"] = _device_fold_ceiling(cache)
    art.update(smoke=smoke)

    if full_corpus_dir and os.path.isdir(full_corpus_dir):
        from bench import make_engine
        from surge_tpu.replay.engine import ResidentWire

        wire_dir = os.path.join(full_corpus_dir, "wire")
        if not os.path.isdir(wire_dir):
            from bench import load_corpus
            make_engine().pack_resident(
                load_corpus(full_corpus_dir).events).save(wire_dir)
        fwire = ResidentWire.load(wire_dir)
        fexpected = {
            "count": np.load(os.path.join(full_corpus_dir,
                                          "expected_count.npy")),
            "version": np.load(os.path.join(full_corpus_dir,
                                            "expected_version.npy")),
        }
        full: dict = {"num_events": int(fwire.num_events), "configs": []}
        art.update(full=full)
        contenders = [dict()]  # all-auto defaults (dense + assoc where legal)
        if best:
            contenders.append({k: best[k] for k in
                               ("dispatch", "unroll", "time_chunk", "tile",
                                "layout", "batch", "chunk_mb") if k in best})
        contenders.append(dict(chunk_mb=16))
        contenders.append(dict(time_chunk=64))  # bench default: pad 1.65→1.32
        contenders.append(dict(tile="xla", layout="flat"))  # r4 baseline delta
        seen: set = set()
        for kw in contenders:
            key = tuple(sorted({**_RUN_CONFIG_DEFAULTS, **kw}.items()))
            if key in seen:
                continue
            seen.add(key)
            row = _run_config(fwire, fexpected, passes=2, **kw)
            full["configs"].append(row)
            art.update(full=full)
        for segments in (4, 8):
            row = _run_streamed(fwire, fexpected, segments)
            full["configs"].append(row)
            art.update(full=full)
        fok = [c for c in full["configs"]
               if c.get("verified") and "events_per_sec" in c]
        full["best"] = max(fok, key=lambda c: c["events_per_sec"]) if fok else {}
        art.update(full=full)

    # every model family + the collective programs, verified on this backend
    # (compile-heavy ~5 min — run LAST so a window drop keeps the perf rows,
    # banked row-by-row so a drop mid-family keeps the earlier families)
    _verify_families(on_row=lambda rows: art.update(families=rows))

    art.update(done=True)
    return best


if __name__ == "__main__":
    full_dir = sys.argv[1] if len(sys.argv) > 1 else None
    best = run_sweep(full_corpus_dir=full_dir)
    print(json.dumps({"best": best}), flush=True)
