#!/bin/sh
# Regenerate the Python protobuf bindings (message classes only; the gRPC service
# glue is hand-written in surge_tpu/multilanguage/service.py because grpcio-tools
# is not in the image).
set -e
cd "$(dirname "$0")/.."
protoc -I proto --python_out=surge_tpu/multilanguage proto/multilanguage.proto
protoc -I proto --python_out=surge_tpu/remote proto/node_transport.proto
protoc -I proto --python_out=surge_tpu/admin proto/admin.proto
protoc -I proto --python_out=surge_tpu/log proto/log_service.proto
protoc -I proto --python_out=surge_tpu/remote proto/control_plane.proto
echo "generated: surge_tpu/multilanguage/multilanguage_pb2.py surge_tpu/remote/node_transport_pb2.py surge_tpu/admin/admin_pb2.py surge_tpu/log/log_service_pb2.py surge_tpu/remote/control_plane_pb2.py"
