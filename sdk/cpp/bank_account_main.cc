// BankAccount sample over the surge C++ SDK — the reference's C# sample role
// (multilanguage-csharp-sdk Sample + SurgeEngine.cs:12-80): the app owns its
// domain types and serialization (payloads are opaque to the engine), hosts
// the BusinessLogic callbacks, and drives commands through the gateway.
//
//   bank_account <gateway_host> <gateway_port> <business_port> [scenario]
//
// Starts the BusinessLogic service on <business_port>, prints
// "READY <bound_port>" on stdout, and (with "scenario") runs the end-to-end
// bank-account flow against the gateway, exiting 0 only if every step —
// including a rejection — behaves exactly as specified.

#include <unistd.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <sstream>
#include <string>
#include <vector>

#include "surge_sdk.h"

namespace {

// state payload: "owner|balance_cents"; command payloads:
// "create|owner|cents", "credit|cents", "debit|cents";
// event payloads: "created|owner|cents", "credited|cents", "debited|cents"
std::vector<std::string> split(const std::string& s) {
  std::vector<std::string> out;
  std::stringstream ss(s);
  std::string part;
  while (std::getline(ss, part, '|')) out.push_back(part);
  return out;
}

long balance_of(const std::string& state) { return atol(split(state)[1].c_str()); }

std::vector<std::string> process_command(
    const std::optional<std::string>& state, const std::string& command) {
  auto parts = split(command);
  if (parts[0] == "create") {
    if (state.has_value()) return {};  // idempotent create: no new events
    return {"created|" + parts[1] + "|" + parts[2]};
  }
  if (!state.has_value())
    throw surge::CommandRejected("account does not exist");
  if (parts[0] == "credit") return {"credited|" + parts[1]};
  if (parts[0] == "debit") {
    long amount = atol(parts[1].c_str());
    if (amount > balance_of(*state))
      throw surge::CommandRejected("insufficient funds");
    return {"debited|" + parts[1]};
  }
  throw surge::CommandRejected("unknown command: " + parts[0]);
}

std::optional<std::string> handle_events(
    const std::optional<std::string>& state,
    const std::vector<std::string>& events) {
  std::optional<std::string> current = state;
  for (const auto& ev : events) {
    auto parts = split(ev);
    if (parts[0] == "created") {
      current = parts[1] + "|" + parts[2];
    } else if (current.has_value()) {
      auto st = split(*current);
      long bal = atol(st[1].c_str());
      long amt = atol(parts[1].c_str());
      bal += parts[0] == "credited" ? amt : -amt;
      current = st[0] + "|" + std::to_string(bal);
    }
  }
  return current;
}

int fail(const char* what, const std::string& detail) {
  fprintf(stderr, "FAIL %s: %s\n", what, detail.c_str());
  return 1;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s <gateway_host> <gateway_port> <business_port> "
                    "[scenario]\n", argv[0]);
    return 2;
  }
  surge::CqrsModel model{process_command, handle_events};
  surge::SurgeEngine engine(model);
  int bound = engine.start_business_service(atoi(argv[3]));
  if (bound < 0) return fail("bind", "business service port");
  printf("READY %d\n", bound);
  fflush(stdout);

  if (argc < 5 || strcmp(argv[4], "scenario") != 0) {
    for (;;) pause();  // serve callbacks until killed
  }

  // the sidecar comes up concurrently (it needs OUR port first): retry the
  // gateway connection for up to ~15s
  std::string error;
  bool connected = false;
  for (int i = 0; i < 75 && !connected; i++) {
    connected = engine.connect_gateway(argv[1], atoi(argv[2]), &error);
    if (!connected) usleep(200 * 1000);
  }
  if (!connected) return fail("connect", error);

  // the engine reports "up" only once its regions finish initializing; on a
  // loaded host that can lag the gateway bind — poll like a real app would
  std::string health;
  for (int i = 0; i < 100 && health != "up"; i++) {
    health = engine.gateway_health(&error);
    if (health != "up") usleep(200 * 1000);
  }
  if (health != "up") return fail("health", "last=" + health + " " + error);

  auto r = engine.forward_command("acct-cpp-1", "create|ada|1000");
  if (!r.ok || !r.state || balance_of(*r.state) != 1000)
    return fail("create", r.error + r.rejection);

  r = engine.forward_command("acct-cpp-1", "credit|250");
  if (!r.ok || balance_of(*r.state) != 1250) return fail("credit", r.error);

  r = engine.forward_command("acct-cpp-1", "debit|1200");
  if (!r.ok || balance_of(*r.state) != 50) return fail("debit", r.error);

  // over-debit must surface the app's own rejection text through the engine
  r = engine.forward_command("acct-cpp-1", "debit|100");
  if (r.ok || r.rejection.find("insufficient funds") == std::string::npos)
    return fail("rejection", r.error + r.rejection);

  auto [found, state] = engine.get_state("acct-cpp-1", &error);
  if (!found || balance_of(state) != 50) return fail("get_state", error);

  auto [missing_found, _] = engine.get_state("acct-cpp-nope", &error);
  if (missing_found) return fail("missing_state", "expected absent");

  // a second account proves per-aggregate isolation
  r = engine.forward_command("acct-cpp-2", "create|bob|5");
  if (!r.ok || balance_of(*r.state) != 5) return fail("create2", r.error);

  printf("SCENARIO PASS\n");
  fflush(stdout);
  engine.stop();
  return 0;
}
