#!/bin/sh
# Build the surge C++ SDK + BankAccount sample against the system libnghttp2
# and libprotobuf (protoc generates the message classes into build/).
set -e
cd "$(dirname "$0")"
mkdir -p build
protoc -I ../../proto --cpp_out=build ../../proto/multilanguage.proto
g++ -O2 -std=c++17 -Wall -Ibuild -I. \
    -o build/bank_account \
    bank_account_main.cc surge_sdk.cc build/multilanguage.pb.cc \
    -l:libnghttp2.so.14 -lprotobuf -lpthread
echo "built: sdk/cpp/build/bank_account"
