// Hand-written declarations for the subset of the system libnghttp2 ABI the
// surge C++ SDK uses (the image ships /lib/x86_64-linux-gnu/libnghttp2.so.14,
// v1.52, without development headers). These mirror the stable public API of
// nghttp2 — the same role the reference's C# SDK fills with Grpc.Core's
// native transport (SurgeEngine.cs:12-80): a real HTTP/2 stack under a thin
// language binding. Signatures are exercised end-to-end against grpc-python
// by tests/test_cpp_sdk.py, so any ABI drift fails loudly there.
#pragma once

#include <stddef.h>
#include <stdint.h>
#include <sys/types.h>

extern "C" {

typedef struct nghttp2_session nghttp2_session;
typedef struct nghttp2_session_callbacks nghttp2_session_callbacks;

typedef struct {
  size_t length;
  int32_t stream_id;
  uint8_t type;
  uint8_t flags;
  uint8_t reserved;
} nghttp2_frame_hd;

// the real nghttp2_frame is a union of per-type structs; every variant begins
// with the frame header, which is all the SDK reads
typedef struct {
  nghttp2_frame_hd hd;
} nghttp2_frame;

typedef struct {
  uint8_t *name;
  uint8_t *value;
  size_t namelen;
  size_t valuelen;
  uint8_t flags;
} nghttp2_nv;

typedef union {
  int fd;
  void *ptr;
} nghttp2_data_source;

typedef ssize_t (*nghttp2_data_source_read_callback)(
    nghttp2_session *session, int32_t stream_id, uint8_t *buf, size_t length,
    uint32_t *data_flags, nghttp2_data_source *source, void *user_data);

typedef struct {
  nghttp2_data_source source;
  nghttp2_data_source_read_callback read_callback;
} nghttp2_data_provider;

typedef struct {
  int32_t settings_id;
  uint32_t value;
} nghttp2_settings_entry;

// frame types
enum {
  NGHTTP2_DATA = 0,
  NGHTTP2_HEADERS = 1,
  NGHTTP2_RST_STREAM = 3,
  NGHTTP2_SETTINGS = 4,
  NGHTTP2_GOAWAY = 7,
  NGHTTP2_WINDOW_UPDATE = 8,
};
// frame flags
enum {
  NGHTTP2_FLAG_NONE = 0,
  NGHTTP2_FLAG_END_STREAM = 0x01,
  NGHTTP2_FLAG_END_HEADERS = 0x04,
};
// data source flags
enum {
  NGHTTP2_DATA_FLAG_NONE = 0,
  NGHTTP2_DATA_FLAG_EOF = 0x01,
  NGHTTP2_DATA_FLAG_NO_END_STREAM = 0x02,
};
// nv flags
enum { NGHTTP2_NV_FLAG_NONE = 0 };

typedef ssize_t (*nghttp2_send_callback)(nghttp2_session *session,
                                         const uint8_t *data, size_t length,
                                         int flags, void *user_data);
typedef int (*nghttp2_on_frame_recv_callback)(nghttp2_session *session,
                                              const nghttp2_frame *frame,
                                              void *user_data);
typedef int (*nghttp2_on_data_chunk_recv_callback)(nghttp2_session *session,
                                                   uint8_t flags,
                                                   int32_t stream_id,
                                                   const uint8_t *data,
                                                   size_t len, void *user_data);
typedef int (*nghttp2_on_header_callback)(nghttp2_session *session,
                                          const nghttp2_frame *frame,
                                          const uint8_t *name, size_t namelen,
                                          const uint8_t *value, size_t valuelen,
                                          uint8_t flags, void *user_data);
typedef int (*nghttp2_on_stream_close_callback)(nghttp2_session *session,
                                                int32_t stream_id,
                                                uint32_t error_code,
                                                void *user_data);

int nghttp2_session_callbacks_new(nghttp2_session_callbacks **callbacks_ptr);
void nghttp2_session_callbacks_del(nghttp2_session_callbacks *callbacks);
void nghttp2_session_callbacks_set_send_callback(
    nghttp2_session_callbacks *cbs, nghttp2_send_callback cb);
void nghttp2_session_callbacks_set_on_frame_recv_callback(
    nghttp2_session_callbacks *cbs, nghttp2_on_frame_recv_callback cb);
void nghttp2_session_callbacks_set_on_data_chunk_recv_callback(
    nghttp2_session_callbacks *cbs, nghttp2_on_data_chunk_recv_callback cb);
void nghttp2_session_callbacks_set_on_header_callback(
    nghttp2_session_callbacks *cbs, nghttp2_on_header_callback cb);
void nghttp2_session_callbacks_set_on_stream_close_callback(
    nghttp2_session_callbacks *cbs, nghttp2_on_stream_close_callback cb);

int nghttp2_session_client_new(nghttp2_session **session_ptr,
                               const nghttp2_session_callbacks *callbacks,
                               void *user_data);
int nghttp2_session_server_new(nghttp2_session **session_ptr,
                               const nghttp2_session_callbacks *callbacks,
                               void *user_data);
void nghttp2_session_del(nghttp2_session *session);

int nghttp2_submit_settings(nghttp2_session *session, uint8_t flags,
                            const nghttp2_settings_entry *iv, size_t niv);
int32_t nghttp2_submit_request(nghttp2_session *session, const void *pri_spec,
                               const nghttp2_nv *nva, size_t nvlen,
                               const nghttp2_data_provider *data_prd,
                               void *stream_user_data);
int nghttp2_submit_response(nghttp2_session *session, int32_t stream_id,
                            const nghttp2_nv *nva, size_t nvlen,
                            const nghttp2_data_provider *data_prd);
int nghttp2_submit_trailer(nghttp2_session *session, int32_t stream_id,
                           const nghttp2_nv *nva, size_t nvlen);

int nghttp2_session_send(nghttp2_session *session);
ssize_t nghttp2_session_mem_recv(nghttp2_session *session, const uint8_t *in,
                                 size_t inlen);
int nghttp2_session_want_read(nghttp2_session *session);
int nghttp2_session_want_write(nghttp2_session *session);

}  // extern "C"
