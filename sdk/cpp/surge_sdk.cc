#include "surge_sdk.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>

#include "multilanguage.pb.h"
#include "nghttp2_api.h"

namespace surge {
namespace {

// 5-byte gRPC message framing: 1 byte compressed flag + u32 big-endian length.
std::string frame_message(const std::string& payload) {
  std::string out;
  out.reserve(payload.size() + 5);
  out.push_back('\0');
  uint32_t n = static_cast<uint32_t>(payload.size());
  out.push_back(static_cast<char>((n >> 24) & 0xFF));
  out.push_back(static_cast<char>((n >> 16) & 0xFF));
  out.push_back(static_cast<char>((n >> 8) & 0xFF));
  out.push_back(static_cast<char>(n & 0xFF));
  out.append(payload);
  return out;
}

bool unframe_message(const std::string& data, std::string* payload) {
  if (data.size() < 5) return false;
  uint32_t n = (static_cast<uint8_t>(data[1]) << 24) |
               (static_cast<uint8_t>(data[2]) << 16) |
               (static_cast<uint8_t>(data[3]) << 8) |
               static_cast<uint8_t>(data[4]);
  if (data.size() < 5 + n) return false;
  payload->assign(data, 5, n);
  return true;
}

nghttp2_nv make_nv(const char* name, const std::string& value) {
  nghttp2_nv nv;
  nv.name = reinterpret_cast<uint8_t*>(const_cast<char*>(name));
  nv.namelen = strlen(name);
  nv.value = reinterpret_cast<uint8_t*>(const_cast<char*>(value.data()));
  nv.valuelen = value.size();
  nv.flags = NGHTTP2_NV_FLAG_NONE;
  return nv;
}

// Pump the session: flush pending writes, then block (up to timeout) for
// readable bytes and feed them in. Returns false on EOF/error.
bool pump(nghttp2_session* session, int fd, int timeout_ms) {
  while (nghttp2_session_want_write(session)) {
    if (nghttp2_session_send(session) != 0) return false;
  }
  struct pollfd p = {fd, POLLIN, 0};
  int r = ::poll(&p, 1, timeout_ms);
  if (r <= 0) return r == 0;  // timeout is not an error; caller loops
  uint8_t buf[16384];
  ssize_t n = ::recv(fd, buf, sizeof(buf), 0);
  if (n <= 0) return false;
  if (nghttp2_session_mem_recv(session, buf, static_cast<size_t>(n)) < 0)
    return false;
  while (nghttp2_session_want_write(session)) {
    if (nghttp2_session_send(session) != 0) return false;
  }
  return true;
}

struct OutBuffer {
  std::string data;
  size_t offset = 0;
  bool send_trailers = false;  // server responses end with grpc-status trailers
};

ssize_t out_read_cb(nghttp2_session* session, int32_t stream_id, uint8_t* buf,
                    size_t length, uint32_t* data_flags,
                    nghttp2_data_source* source, void*) {
  OutBuffer* out = static_cast<OutBuffer*>(source->ptr);
  size_t left = out->data.size() - out->offset;
  size_t n = left < length ? left : length;
  memcpy(buf, out->data.data() + out->offset, n);
  out->offset += n;
  if (out->offset == out->data.size()) {
    *data_flags |= NGHTTP2_DATA_FLAG_EOF;
    if (out->send_trailers) {
      *data_flags |= NGHTTP2_DATA_FLAG_NO_END_STREAM;
      static const std::string kZero = "0";
      nghttp2_nv trailers[] = {make_nv("grpc-status", kZero)};
      nghttp2_submit_trailer(session, stream_id, trailers, 1);
    }
  }
  return static_cast<ssize_t>(n);
}

}  // namespace

// ---- client ----------------------------------------------------------------

struct StreamResult {
  std::string body;
  bool closed = false;
  uint32_t error_code = 0;
  int grpc_status = 0;
  std::string grpc_message;
};

struct GrpcConnection::Impl {
  std::string host;
  int port;
  int fd = -1;
  nghttp2_session* session = nullptr;
  std::map<int32_t, StreamResult> streams;
  std::mutex mutex;  // calls are serialized

  static int on_header(nghttp2_session*, const nghttp2_frame* frame,
                       const uint8_t* name, size_t namelen,
                       const uint8_t* value, size_t valuelen, uint8_t,
                       void* user_data) {
    Impl* self = static_cast<Impl*>(user_data);
    auto it = self->streams.find(frame->hd.stream_id);
    if (it == self->streams.end()) return 0;
    std::string n(reinterpret_cast<const char*>(name), namelen);
    std::string v(reinterpret_cast<const char*>(value), valuelen);
    if (n == "grpc-status") it->second.grpc_status = atoi(v.c_str());
    if (n == "grpc-message") it->second.grpc_message = v;
    return 0;
  }

  static int on_data(nghttp2_session*, uint8_t, int32_t stream_id,
                     const uint8_t* data, size_t len, void* user_data) {
    Impl* self = static_cast<Impl*>(user_data);
    auto it = self->streams.find(stream_id);
    if (it != self->streams.end())
      it->second.body.append(reinterpret_cast<const char*>(data), len);
    return 0;
  }

  static int on_close(nghttp2_session*, int32_t stream_id, uint32_t error_code,
                      void* user_data) {
    Impl* self = static_cast<Impl*>(user_data);
    auto it = self->streams.find(stream_id);
    if (it != self->streams.end()) {
      it->second.closed = true;
      it->second.error_code = error_code;
    }
    return 0;
  }
};

GrpcConnection::GrpcConnection(std::string host, int port)
    : impl_(new Impl{std::move(host), port}) {}

GrpcConnection::~GrpcConnection() { close(); }

bool GrpcConnection::connect(std::string* error) {
  Impl* im = impl_.get();
  im->fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (im->fd < 0) {
    *error = "socket() failed";
    return false;
  }
  int one = 1;
  setsockopt(im->fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(im->port));
  inet_pton(AF_INET, im->host.c_str(), &addr.sin_addr);
  if (::connect(im->fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    *error = "connect() to " + im->host + " failed";
    return false;
  }

  nghttp2_session_callbacks* cbs = nullptr;
  nghttp2_session_callbacks_new(&cbs);
  nghttp2_session_callbacks_set_on_header_callback(cbs, Impl::on_header);
  nghttp2_session_callbacks_set_on_data_chunk_recv_callback(cbs, Impl::on_data);
  nghttp2_session_callbacks_set_on_stream_close_callback(cbs, Impl::on_close);
  nghttp2_session_callbacks_set_send_callback(
      cbs, [](nghttp2_session*, const uint8_t* data, size_t length, int,
              void* user_data) -> ssize_t {
        Impl* self = static_cast<Impl*>(user_data);
        ssize_t sent = ::send(self->fd, data, length, 0);
        return sent < 0 ? -902 : sent;
      });
  nghttp2_session_client_new(&im->session, cbs, im);
  nghttp2_session_callbacks_del(cbs);
  nghttp2_submit_settings(im->session, NGHTTP2_FLAG_NONE, nullptr, 0);
  if (nghttp2_session_send(im->session) != 0) {
    *error = "HTTP/2 handshake send failed";
    return false;
  }
  return true;
}

bool GrpcConnection::call(const std::string& path, const std::string& request,
                          std::string* response, std::string* error) {
  Impl* im = impl_.get();
  std::lock_guard<std::mutex> lock(im->mutex);
  if (im->session == nullptr) {
    *error = "not connected";
    return false;
  }
  OutBuffer out;
  out.data = frame_message(request);
  nghttp2_data_provider provider;
  provider.source.ptr = &out;
  provider.read_callback = out_read_cb;
  static const std::string kPost = "POST", kScheme = "http",
                           kContentType = "application/grpc", kTe = "trailers";
  nghttp2_nv nva[] = {
      make_nv(":method", kPost),        make_nv(":scheme", kScheme),
      make_nv(":path", path),           make_nv(":authority", im->host),
      make_nv("content-type", kContentType), make_nv("te", kTe),
  };
  int32_t stream_id = nghttp2_submit_request(im->session, nullptr, nva, 6,
                                             &provider, nullptr);
  if (stream_id < 0) {
    *error = "submit_request failed";
    return false;
  }
  im->streams[stream_id] = StreamResult{};
  // pump until the stream closes, bounded by WALL TIME (30s, mirroring the
  // engine's command timeout) — an iteration cap would misreport large
  // responses arriving in many recv chunks as timeouts
  const auto deadline =
      std::chrono::steady_clock::now() + std::chrono::seconds(30);
  while (std::chrono::steady_clock::now() < deadline) {
    StreamResult& st = im->streams[stream_id];
    if (st.closed) break;
    if (!pump(im->session, im->fd, 100)) {
      im->streams.erase(stream_id);
      *error = "connection lost mid-call";
      return false;
    }
  }
  StreamResult st = im->streams[stream_id];
  im->streams.erase(stream_id);
  if (!st.closed) {
    *error = "rpc timed out";
    return false;
  }
  if (st.error_code != 0 || st.grpc_status != 0) {
    *error = "rpc failed: grpc-status=" + std::to_string(st.grpc_status) +
             (st.grpc_message.empty() ? "" : " (" + st.grpc_message + ")");
    return false;
  }
  if (!unframe_message(st.body, response)) {
    *error = "malformed grpc response framing";
    return false;
  }
  return true;
}

void GrpcConnection::close() {
  Impl* im = impl_.get();
  if (im->session != nullptr) {
    nghttp2_session_del(im->session);
    im->session = nullptr;
  }
  if (im->fd >= 0) {
    ::close(im->fd);
    im->fd = -1;
  }
}

// ---- server ----------------------------------------------------------------

namespace {

struct ServerStream {
  std::string path;
  std::string body;
  OutBuffer out;  // response buffer must outlive the data provider
};

struct ServerConn {
  nghttp2_session* session = nullptr;
  int fd = -1;
  std::map<int32_t, ServerStream> streams;
  const std::map<std::string, UnaryHandler>* handlers = nullptr;

  void dispatch(int32_t stream_id) {
    ServerStream& st = streams[stream_id];
    static const std::string kStatus200 = "200",
                             kContentType = "application/grpc";
    auto it = handlers->find(st.path);
    if (it == handlers->end()) {
      static const std::string kUnimplemented = "12";
      nghttp2_nv nva[] = {make_nv(":status", kStatus200),
                          make_nv("content-type", kContentType),
                          make_nv("grpc-status", kUnimplemented)};
      nghttp2_submit_response(session, stream_id, nva, 3, nullptr);
      return;
    }
    std::string request;
    std::string reply_bytes;
    bool handler_ok = true;
    if (!unframe_message(st.body, &request)) {
      // malformed/absent gRPC framing must NOT read as a successful empty
      // reply — answer INVALID_ARGUMENT so the client sees the error
      static const std::string kInvalidArgument = "3";
      nghttp2_nv nva[] = {make_nv(":status", kStatus200),
                          make_nv("content-type", kContentType),
                          make_nv("grpc-status", kInvalidArgument)};
      nghttp2_submit_response(session, stream_id, nva, 3, nullptr);
      return;
    }
    {
      // an app exception must never unwind through the C library frames below
      // us (std::terminate); surface it as INTERNAL like the Python SDK does
      try {
        reply_bytes = it->second(request);
      } catch (const std::exception& e) {
        fprintf(stderr, "handler %s threw: %s\n", st.path.c_str(), e.what());
        handler_ok = false;
      } catch (...) {
        fprintf(stderr, "handler %s threw a non-std exception\n",
                st.path.c_str());
        handler_ok = false;
      }
    }
    if (!handler_ok) {
      static const std::string kInternal = "13";
      nghttp2_nv nva[] = {make_nv(":status", kStatus200),
                          make_nv("content-type", kContentType),
                          make_nv("grpc-status", kInternal)};
      nghttp2_submit_response(session, stream_id, nva, 3, nullptr);
      return;
    }
    st.out.data = frame_message(reply_bytes);
    st.out.send_trailers = true;
    nghttp2_data_provider provider;
    provider.source.ptr = &st.out;
    provider.read_callback = out_read_cb;
    nghttp2_nv nva[] = {make_nv(":status", kStatus200),
                        make_nv("content-type", kContentType)};
    nghttp2_submit_response(session, stream_id, nva, 2, &provider);
  }

  static int on_header(nghttp2_session*, const nghttp2_frame* frame,
                       const uint8_t* name, size_t namelen,
                       const uint8_t* value, size_t valuelen, uint8_t,
                       void* user_data) {
    ServerConn* self = static_cast<ServerConn*>(user_data);
    std::string n(reinterpret_cast<const char*>(name), namelen);
    if (n == ":path")
      self->streams[frame->hd.stream_id].path =
          std::string(reinterpret_cast<const char*>(value), valuelen);
    return 0;
  }

  static int on_data(nghttp2_session*, uint8_t, int32_t stream_id,
                     const uint8_t* data, size_t len, void* user_data) {
    ServerConn* self = static_cast<ServerConn*>(user_data);
    self->streams[stream_id].body.append(reinterpret_cast<const char*>(data),
                                         len);
    return 0;
  }

  static int on_frame_recv(nghttp2_session*, const nghttp2_frame* frame,
                           void* user_data) {
    ServerConn* self = static_cast<ServerConn*>(user_data);
    if ((frame->hd.type == NGHTTP2_DATA || frame->hd.type == NGHTTP2_HEADERS) &&
        (frame->hd.flags & NGHTTP2_FLAG_END_STREAM) &&
        self->streams.count(frame->hd.stream_id)) {
      self->dispatch(frame->hd.stream_id);
    }
    return 0;
  }

  static int on_close(nghttp2_session*, int32_t stream_id, uint32_t,
                      void* user_data) {
    static_cast<ServerConn*>(user_data)->streams.erase(stream_id);
    return 0;
  }
};

}  // namespace

GrpcServer::GrpcServer() = default;
GrpcServer::~GrpcServer() { stop(); }

void GrpcServer::handle(const std::string& path, UnaryHandler handler) {
  handlers_[path] = std::move(handler);
}

int GrpcServer::start(int port) {
  listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, "127.0.0.1", &addr.sin_addr);
  if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
    return -1;
  ::listen(listen_fd_, 8);
  socklen_t len = sizeof(addr);
  getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
  thread_ = std::thread([this] { accept_loop(); });
  return ntohs(addr.sin_port);
}

void GrpcServer::accept_loop() {
  while (!stopping_) {
    struct pollfd p = {listen_fd_, POLLIN, 0};
    if (::poll(&p, 1, 200) <= 0) continue;
    int fd = ::accept(listen_fd_, nullptr, nullptr);
    if (fd < 0) continue;
    serve_connection(fd);  // the sidecar holds one channel; serve it fully
    ::close(fd);
  }
}

void GrpcServer::serve_connection(int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  ServerConn conn;
  conn.fd = fd;
  conn.handlers = &handlers_;
  nghttp2_session_callbacks* cbs = nullptr;
  nghttp2_session_callbacks_new(&cbs);
  nghttp2_session_callbacks_set_on_header_callback(cbs, ServerConn::on_header);
  nghttp2_session_callbacks_set_on_data_chunk_recv_callback(cbs,
                                                            ServerConn::on_data);
  nghttp2_session_callbacks_set_on_frame_recv_callback(
      cbs, ServerConn::on_frame_recv);
  nghttp2_session_callbacks_set_on_stream_close_callback(cbs,
                                                         ServerConn::on_close);
  nghttp2_session_callbacks_set_send_callback(
      cbs, [](nghttp2_session*, const uint8_t* data, size_t length, int,
              void* user_data) -> ssize_t {
        ServerConn* self = static_cast<ServerConn*>(user_data);
        ssize_t sent = ::send(self->fd, data, length, 0);
        return sent < 0 ? -902 : sent;
      });
  nghttp2_session_server_new(&conn.session, cbs, &conn);
  nghttp2_session_callbacks_del(cbs);
  nghttp2_submit_settings(conn.session, NGHTTP2_FLAG_NONE, nullptr, 0);

  while (!stopping_ && (nghttp2_session_want_read(conn.session) ||
                        nghttp2_session_want_write(conn.session))) {
    if (!pump(conn.session, fd, 200)) break;
  }
  nghttp2_session_del(conn.session);
}

void GrpcServer::stop() {
  stopping_ = true;
  if (thread_.joinable()) thread_.join();
  if (listen_fd_ >= 0) {
    ::close(listen_fd_);
    listen_fd_ = -1;
  }
}

// ---- SDK surface -----------------------------------------------------------

namespace ml = surge_tpu::multilanguage;

static const char kBusinessService[] = "/surge_tpu.multilanguage.BusinessLogic";
static const char kGatewayService[] =
    "/surge_tpu.multilanguage.MultilanguageGateway";

SurgeEngine::SurgeEngine(CqrsModel model) : model_(std::move(model)) {}
SurgeEngine::~SurgeEngine() { stop(); }

int SurgeEngine::start_business_service(int port) {
  server_.handle(
      std::string(kBusinessService) + "/ProcessCommand",
      [this](const std::string& raw) {
        ml::ProcessCommandRequest req;
        req.ParseFromString(raw);
        ml::ProcessCommandReply reply;
        std::optional<std::string> state;
        if (req.state().exists()) state = req.state().payload();
        try {
          auto events = model_.process_command(state, req.command().payload());
          reply.set_success(true);
          for (const auto& ev : events) {
            ml::DomainEvent* out = reply.add_events();
            out->set_aggregate_id(req.command().aggregate_id());
            out->set_payload(ev);
          }
        } catch (const CommandRejected& rej) {
          reply.set_success(false);
          reply.set_rejection(rej.what());
        }
        return reply.SerializeAsString();
      });
  server_.handle(
      std::string(kBusinessService) + "/HandleEvents",
      [this](const std::string& raw) {
        ml::HandleEventsRequest req;
        req.ParseFromString(raw);
        std::optional<std::string> state;
        if (req.state().exists()) state = req.state().payload();
        std::vector<std::string> events;
        std::string aggregate_id = req.state().aggregate_id();
        for (const auto& ev : req.events()) {
          events.push_back(ev.payload());
          aggregate_id = ev.aggregate_id();
        }
        auto new_state = model_.handle_events(state, events);
        ml::HandleEventsReply reply;
        reply.mutable_state()->set_aggregate_id(aggregate_id);
        if (new_state.has_value()) {
          reply.mutable_state()->set_exists(true);
          reply.mutable_state()->set_payload(*new_state);
        } else {
          reply.mutable_state()->set_exists(false);
        }
        return reply.SerializeAsString();
      });
  server_.handle(std::string(kBusinessService) + "/HealthCheck",
                 [](const std::string&) {
                   ml::HealthReply reply;
                   reply.set_status("up");
                   return reply.SerializeAsString();
                 });
  return server_.start(port);
}

bool SurgeEngine::connect_gateway(const std::string& host, int port,
                                  std::string* error) {
  gateway_.reset(new GrpcConnection(host, port));
  return gateway_->connect(error);
}

ForwardResult SurgeEngine::forward_command(const std::string& aggregate_id,
                                           const std::string& command_payload) {
  ForwardResult result;
  ml::ForwardCommandRequest req;
  req.mutable_command()->set_aggregate_id(aggregate_id);
  req.mutable_command()->set_payload(command_payload);
  std::string raw;
  if (!gateway_->call(std::string(kGatewayService) + "/ForwardCommand",
                      req.SerializeAsString(), &raw, &result.error)) {
    return result;
  }
  ml::ForwardCommandReply reply;
  reply.ParseFromString(raw);
  if (!reply.success()) {
    result.rejection = reply.rejection();
    return result;
  }
  result.ok = true;
  if (reply.state().exists()) result.state = reply.state().payload();
  return result;
}

std::pair<bool, std::string> SurgeEngine::get_state(
    const std::string& aggregate_id, std::string* error) {
  ml::GetStateRequest req;
  req.set_aggregate_id(aggregate_id);
  std::string raw;
  if (!gateway_->call(std::string(kGatewayService) + "/GetState",
                      req.SerializeAsString(), &raw, error)) {
    return {false, ""};
  }
  ml::GetStateReply reply;
  reply.ParseFromString(raw);
  if (!reply.state().exists()) return {false, ""};
  return {true, reply.state().payload()};
}

std::string SurgeEngine::gateway_health(std::string* error) {
  ml::HealthRequest req;
  std::string raw;
  if (!gateway_->call(std::string(kGatewayService) + "/HealthCheck",
                      req.SerializeAsString(), &raw, error)) {
    return "";
  }
  ml::HealthReply reply;
  reply.ParseFromString(raw);
  return reply.status();
}

void SurgeEngine::stop() {
  if (gateway_) gateway_->close();
  server_.stop();
}

}  // namespace surge
