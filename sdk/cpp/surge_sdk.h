// surge C++ SDK — the second-language proof of the multilanguage sidecar
// protocol (the role of the reference's C# SDK, SurgeEngine.cs:12-80 +
// CqrsModel.cs): a native app hosts the BusinessLogic service (engine -> app
// callbacks) and drives the engine through the MultilanguageGateway service
// (app -> engine), speaking real gRPC over HTTP/2 (system libnghttp2 +
// libprotobuf) against the Python sidecar — proto/multilanguage.proto is the
// whole contract, exactly as the reference's proto is for its SDKs.
#pragma once

#include <atomic>
#include <cstdint>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <optional>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

namespace surge {

// ---- transport -------------------------------------------------------------

// One blocking gRPC-over-HTTP/2 client connection (unary calls only).
class GrpcConnection {
 public:
  GrpcConnection(std::string host, int port);
  ~GrpcConnection();
  GrpcConnection(const GrpcConnection&) = delete;
  GrpcConnection& operator=(const GrpcConnection&) = delete;

  bool connect(std::string* error);
  // Unary call: serialized request in, serialized response out. Returns false
  // on transport/stream failure or non-zero grpc-status.
  bool call(const std::string& path, const std::string& request,
            std::string* response, std::string* error);
  void close();

 private:
  struct Impl;
  std::unique_ptr<Impl> impl_;
};

// Minimal gRPC server hosting unary handlers (one connection at a time — the
// sidecar engine holds exactly one channel to the app).
using UnaryHandler = std::function<std::string(const std::string& request)>;

class GrpcServer {
 public:
  GrpcServer();
  ~GrpcServer();

  void handle(const std::string& path, UnaryHandler handler);
  int start(int port);  // returns bound port (port 0 = ephemeral)
  void stop();

 private:
  void accept_loop();
  void serve_connection(int fd);

  std::map<std::string, UnaryHandler> handlers_;
  int listen_fd_ = -1;
  std::thread thread_;
  std::atomic<bool> stopping_{false};
};

// ---- SDK surface (CQRSModel / SurgeEngine analog) ---------------------------

// Raised by process_command to reject a command (CommandRejectedByApp role).
struct CommandRejected : std::runtime_error {
  using std::runtime_error::runtime_error;
};

// Two pure functions over app-serialized bytes (the app composes its own
// domain serde around them, like the reference SDKs' SerDeser).
struct CqrsModel {
  // (state or nullopt, command payload) -> event payloads; throw
  // CommandRejected to reject.
  std::function<std::vector<std::string>(const std::optional<std::string>&,
                                         const std::string&)>
      process_command;
  // (state or nullopt, event payloads) -> new state (nullopt = delete)
  std::function<std::optional<std::string>(
      const std::optional<std::string>&, const std::vector<std::string>&)>
      handle_events;
};

struct ForwardResult {
  bool ok = false;             // transport + command success
  std::string rejection;       // non-empty when the engine rejected it
  std::optional<std::string> state;  // post-command state payload
  std::string error;           // transport-level failure detail
};

class SurgeEngine {
 public:
  explicit SurgeEngine(CqrsModel model);
  ~SurgeEngine();

  // Host the BusinessLogic service for the sidecar's callbacks.
  int start_business_service(int port = 0);
  // Connect to the sidecar's MultilanguageGateway.
  bool connect_gateway(const std::string& host, int port, std::string* error);

  ForwardResult forward_command(const std::string& aggregate_id,
                                const std::string& command_payload);
  // (found, state payload) — found=false means no such aggregate.
  std::pair<bool, std::string> get_state(const std::string& aggregate_id,
                                         std::string* error);
  std::string gateway_health(std::string* error);

  void stop();

 private:
  CqrsModel model_;
  GrpcServer server_;
  std::unique_ptr<GrpcConnection> gateway_;
};

}  // namespace surge
