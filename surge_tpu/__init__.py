"""surge_tpu — a TPU-native CQRS / event-sourcing framework.

A ground-up re-design of the capabilities of UltimateSoftware/surge (Scala/Akka/Kafka)
for TPU hardware and the JAX/XLA compilation model:

- Typed command engines with single-writer aggregates (asyncio tasks replace Akka actors).
- Transactional event+state publishing to a replicated log (in-memory / file-backed log
  transports with Kafka-compatible semantics: idempotent producers, epochs/fencing,
  read-committed isolation).
- KTable-style materialized state store with watermark bookkeeping.
- The north-star workload: massively parallel aggregate-state replay — the per-aggregate
  ``handle_event`` fold lifted into a batched ``jax.lax.scan`` over event tensors,
  ``vmap``-ed across aggregates and sharded over a ``jax.sharding.Mesh``
  (``replay_backend = "tpu"``).
- Health supervision, metrics, W3C trace propagation, and a gRPC-shaped multilanguage
  bridge, mirroring the reference's component inventory (see SURVEY.md §2).

Reference parity pointers cite the Scala sources as ``file:line`` in docstrings.
"""

__version__ = "0.2.0"

from surge_tpu.config import Config, default_config
from surge_tpu.dsl import (
    CommandFailure,
    CommandRejected,
    CommandSuccess,
    SurgeCommandBusinessLogic,
    SurgeEngine,
    SurgeEngineBuilder,
    create_engine,
)
from surge_tpu.engine.event_dsl import SurgeEventEngine, create_event_engine
from surge_tpu.log import FileLog, InMemoryLog
from surge_tpu.serialization import (
    SerializedMessage,
    SerializedAggregate,
    AggregateReadFormatting,
    AggregateWriteFormatting,
    EventWriteFormatting,
)

__all__ = [
    "CommandFailure",
    "CommandRejected",
    "CommandSuccess",
    "Config",
    "FileLog",
    "InMemoryLog",
    "SurgeCommandBusinessLogic",
    "SurgeEngine",
    "SurgeEngineBuilder",
    "SurgeEventEngine",
    "create_engine",
    "create_event_engine",
    "default_config",
    "SerializedMessage",
    "SerializedAggregate",
    "AggregateReadFormatting",
    "AggregateWriteFormatting",
    "EventWriteFormatting",
    "__version__",
]
