"""Admin service — operator introspection and control for a running engine.

The JMX-suite analog (reference: surge/health/jmx SurgeHealthActor:20-132, MBean
exposing the health registry plus restart/stop controls, behind
``supervisor-actor.jmx-enabled``): a small gRPC service per engine process serving
the health-check tree, the metrics registry export, the supervised-component list,
and restart/stop controls routed through each component's ``Controllable``.
"""

from surge_tpu.admin.server import AdminClient, AdminServer

__all__ = ["AdminClient", "AdminServer"]
