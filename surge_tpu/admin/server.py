"""Admin gRPC server/client over the hand-written service glue (see package doc)."""

from __future__ import annotations

import json
from dataclasses import asdict
from typing import Optional

import grpc

from surge_tpu.admin import admin_pb2 as pb
from surge_tpu.multilanguage.service import (generic_handler, stream_callables,
                                             unary_callables)

SERVICE = "surge_tpu.admin.SurgeAdmin"
METHODS = {
    "GetHealth": (pb.Empty, pb.HealthTreeReply),
    "GetMetrics": (pb.Empty, pb.MetricsReply),
    # OpenMetrics text exposition (the scrape payload over gRPC); reuses the
    # bytes-carrying MetricsReply — routing is by this table, not the
    # descriptor, so no proto regeneration is needed (grpcio-tools absent)
    "GetMetricsText": (pb.Empty, pb.MetricsReply),
    "ListComponents": (pb.Empty, pb.RegistrationsReply),
    "RestartComponent": (pb.ComponentRequest, pb.ComponentReply),
    "StopEngine": (pb.Empty, pb.ComponentReply),
    # log compaction / checkpoint plane (docs/compaction.md). Message reuse,
    # same as GetMetricsText: ComponentRequest.name carries the topic ("" =
    # every compacted topic); the stats ride MetricsReply as JSON
    "CompactLog": (pb.ComponentRequest, pb.MetricsReply),
    "WriteCheckpoint": (pb.Empty, pb.ComponentReply),
    # fault-injection plane (surge_tpu.testing.faults) against the ENGINE's
    # in-process log — the broker-side twin is LogService.ArmFaults.
    # ComponentRequest.name carries "arm:<seed>:<plan>" ("arm:7:flaky-network",
    # "arm:0:{json}"), "disarm", or "status"; stats ride MetricsReply as JSON
    "ArmFaults": (pb.ComponentRequest, pb.MetricsReply),
    # engine flight recorder (surge_tpu.observability.flight): the merge-ready
    # dump envelope as JSON — engine lane events (publisher lane transitions,
    # rebalances, resident-plane moves, health restarts, SLO breaches)
    # interleave with broker DumpFlight dumps on one incident timeline.
    # ComponentRequest.name optionally carries the tail size ("last:50")
    "DumpFlight": (pb.ComponentRequest, pb.MetricsReply),
    # tail-kept trace ring (surge_tpu.tracing.tail): the merge-ready trace
    # dump envelope as JSON — engine-side spans of kept traces assemble with
    # broker DumpTraces dumps into whole command traces
    # (observability/anatomy.py). Same "last:N" tail convention as DumpFlight
    "DumpTraces": (pb.ComponentRequest, pb.MetricsReply),
    # saga plane (surge_tpu.saga). Message reuse as above:
    # StartSaga's ComponentRequest.name carries
    # {"saga_id","definition","ctx"} JSON; SagaStatus's carries a saga id
    # ("" = fleet summary + reconciliation verdict). Results ride
    # MetricsReply as JSON
    "StartSaga": (pb.ComponentRequest, pb.MetricsReply),
    "SagaStatus": (pb.ComponentRequest, pb.MetricsReply),
    # consistency observatory (surge_tpu.observability.audit): the auditor's
    # verdict — ok flag, unresolved-divergence ledger, last-round detail —
    # as JSON on MetricsReply (chaos.py audit / surgetop read this).
    # ComponentRequest.name is unused
    "AuditStatus": (pb.ComponentRequest, pb.MetricsReply),
    # refresh-round ledger (surge_tpu.replay.ledger): the device
    # observatory's per-round padding-waste / per-stage anatomy in the same
    # merge-ready flight envelope (role "ledger"), with the roofline summary
    # riding alongside. Same "last:N" tail convention as DumpFlight
    "DumpReplayLedger": (pb.ComponentRequest, pb.MetricsReply),
    # TPU scan engine over committed columnar segments (surge_tpu.replay.
    # query; docs/replay.md "Query engine"). Message reuse, same as
    # GetMetricsText: ComponentRequest.name carries the query as JSON
    # (ScanQuery / StateQuery json forms), the result rides MetricsReply as
    # JSON rows capped at surge.query.max-rows
    "ScanSegments": (pb.ComponentRequest, pb.MetricsReply),
    "QueryStates": (pb.ComponentRequest, pb.MetricsReply),
    # incremental materialized views (surge_tpu.replay.views; docs/replay.md
    # "Materialized views"). ComponentRequest.name carries the view name
    # ("" / "{}" = the per-view operator summary); the snapshot rides
    # MetricsReply as JSON (sorted keys + rows, top-k applied)
    "QueryView": (pb.ComponentRequest, pb.MetricsReply),
}

#: server-STREAMING methods (same message-reuse discipline):
#: SubscribeView's ComponentRequest.name carries {"view": ..,
#: "from_version": ..} as JSON and each MetricsReply frame is one changefeed
#: entry — a reconciling snapshot (reset) or a per-round delta
STREAM_METHODS = {
    "SubscribeView": (pb.ComponentRequest, pb.MetricsReply),
}


class AdminServer:
    """Serves introspection + control for one engine."""

    def __init__(self, engine, host: str = "127.0.0.1", port: int = 0) -> None:
        self.engine = engine
        self._host = host
        self._port = port
        self._server: Optional[grpc.aio.Server] = None
        self.bound_port: Optional[int] = None

    # -- service implementation ----------------------------------------------------------

    async def GetHealth(self, request, context) -> pb.HealthTreeReply:
        tree = self.engine.health_check()
        return pb.HealthTreeReply(tree_json=json.dumps(asdict(tree)).encode())

    async def GetMetrics(self, request, context) -> pb.MetricsReply:
        reg = self.engine.metrics_registry
        flight = getattr(self.engine, "flight", None)
        return pb.MetricsReply(metrics_json=json.dumps({
            "values": reg.get_metrics(),
            "descriptions": reg.metric_descriptions(),
            # ring occupancy + dropped-event count: the operator's tell that
            # the bounded flight ring wrapped mid-incident
            "flight": flight.stats() if flight is not None else None,
        }).encode())

    async def GetMetricsText(self, request, context) -> pb.MetricsReply:
        """The registry in OpenMetrics text format, health-plane counters
        included — byte-identical to what the HTTP scrape endpoint serves."""
        from surge_tpu.metrics.exposition import health_collector, render_openmetrics

        text = render_openmetrics(
            self.engine.metrics_registry,
            collectors=[health_collector(
                getattr(self.engine, "health_bus", None),
                getattr(self.engine, "health_supervisor", None))])
        return pb.MetricsReply(metrics_json=text.encode())

    async def DumpFlight(self, request, context) -> pb.MetricsReply:
        """The engine flight recorder's merge-ready dump (ring stats —
        occupancy + dropped-event count — ride the envelope, so an operator
        can tell when the bounded ring wrapped mid-incident)."""
        last = None
        name = request.name or ""
        if name.startswith("last:"):
            try:
                last = int(name.partition(":")[2])
            except ValueError:
                last = None
        flight = getattr(self.engine, "flight", None)
        if flight is None:
            return pb.MetricsReply(metrics_json=json.dumps(
                {"error": "engine has no flight recorder"}).encode())
        return pb.MetricsReply(
            metrics_json=json.dumps(flight.dump(last)).encode())

    async def DumpTraces(self, request, context) -> pb.MetricsReply:
        """The engine's tail-kept trace ring as a merge-ready dump (the
        DumpFlight twin for spans). An untraced engine answers an error
        payload — "nothing kept" and "tracing off" must be tellable apart."""
        last = None
        name = request.name or ""
        if name.startswith("last:"):
            try:
                last = int(name.partition(":")[2])
            except ValueError:
                last = None
        ring = getattr(self.engine, "trace_ring", None)
        if ring is None:
            return pb.MetricsReply(metrics_json=json.dumps(
                {"error": "engine has no trace ring (no tracer, or "
                          "surge.trace.tail.enabled=false)"}).encode())
        return pb.MetricsReply(
            metrics_json=json.dumps(ring.dump(last)).encode())

    async def DumpReplayLedger(self, request, context) -> pb.MetricsReply:
        """The refresh-round ledger's merge-ready dump: round / gather /
        query anatomy events plus the roofline summary rollup. An engine
        without the resident plane's observatory answers an error payload."""
        last = None
        name = request.name or ""
        if name.startswith("last:"):
            try:
                last = int(name.partition(":")[2])
            except ValueError:
                last = None
        ledger = getattr(self.engine, "replay_ledger", None)
        if ledger is None:
            return pb.MetricsReply(metrics_json=json.dumps(
                {"error": "engine has no replay ledger"}).encode())
        return pb.MetricsReply(
            metrics_json=json.dumps(ledger.dump(last)).encode())

    async def ListComponents(self, request, context) -> pb.RegistrationsReply:
        return pb.RegistrationsReply(
            names=self.engine.health_supervisor.registered())

    async def RestartComponent(self, request, context) -> pb.ComponentReply:
        """Drive the component's restart through the supervisor (the MBean restart
        op) — same budget and signal emission as a pattern-matched restart."""
        try:
            await self.engine.health_supervisor.restart_component(request.name)
            return pb.ComponentReply(ok=True, detail="restarted")
        except KeyError:
            return pb.ComponentReply(
                ok=False, detail=f"unknown component {request.name!r}")
        except Exception as exc:  # noqa: BLE001 — operator gets the failure back
            return pb.ComponentReply(ok=False, detail=repr(exc))

    async def CompactLog(self, request, context) -> pb.MetricsReply:
        """Force a compaction pass over the engine's compacted topics (or just
        ``request.name``) — the operator-triggered path of the background
        compactor, ratio thresholds bypassed. Returns the per-partition stats."""
        stats = await self.engine.compactor.compact_once(
            request.name or None, force=True)
        return pb.MetricsReply(metrics_json=json.dumps(
            [s.as_dict() for s in stats]).encode())

    async def WriteCheckpoint(self, request, context) -> pb.ComponentReply:
        """Advance the checkpoint materializer to the current end offsets and
        publish a checkpoint now (the pre-maintenance 'bound my next cold
        start' op)."""
        writer = getattr(self.engine, "checkpoint_writer", None)
        if writer is None:
            return pb.ComponentReply(
                ok=False,
                detail="no checkpoint writer (surge.store.checkpoint.path unset)")
        try:
            import asyncio

            ckpt = await asyncio.get_running_loop().run_in_executor(
                None, writer.write_now)
            return pb.ComponentReply(
                ok=True, detail=json.dumps({
                    "seq": ckpt.seq, "aggregates": ckpt.num_aggregates,
                    "events_covered": ckpt.events_covered()}))
        except Exception as exc:  # noqa: BLE001 — operator gets the failure back
            return pb.ComponentReply(ok=False, detail=repr(exc))

    async def StartSaga(self, request, context) -> pb.MetricsReply:
        """Start a saga on this engine's registered SagaManager.
        ``request.name`` carries ``{"saga_id", "definition", "ctx"}`` JSON;
        the started saga's status ledger rides back. Idempotent: the start
        command's deterministic rid collapses re-submissions."""
        try:
            payload = json.loads(request.name or "{}")
            status = await self.engine.start_saga(
                payload["saga_id"], payload["definition"],
                tuple(payload.get("ctx", ())))
            return pb.MetricsReply(metrics_json=json.dumps(status).encode())
        except Exception as exc:  # noqa: BLE001 — errors ride the reply
            return pb.MetricsReply(
                metrics_json=json.dumps({"error": repr(exc)}).encode())

    async def SagaStatus(self, request, context) -> pb.MetricsReply:
        """One saga's ledger (``request.name`` = saga id), or the fleet
        summary + reconciliation verdict (empty name)."""
        try:
            status = await self.engine.saga_status(request.name or "")
            return pb.MetricsReply(metrics_json=json.dumps(status).encode())
        except Exception as exc:  # noqa: BLE001 — errors ride the reply
            return pb.MetricsReply(
                metrics_json=json.dumps({"error": repr(exc)}).encode())

    async def AuditStatus(self, request, context) -> pb.MetricsReply:
        """The consistency auditor's verdict: ``ok`` plus the unresolved
        ledger and last-round detail (``chaos.py audit`` exits on ``ok``)."""
        try:
            status = self.engine.audit_status()
            return pb.MetricsReply(metrics_json=json.dumps(status).encode())
        except Exception as exc:  # noqa: BLE001 — errors ride the reply
            return pb.MetricsReply(
                metrics_json=json.dumps({"error": repr(exc)}).encode())

    async def ArmFaults(self, request, context) -> pb.MetricsReply:
        """Arm/disarm/inspect a fault plane on the engine's IN-PROCESS log
        (FileLog WAL sites; chaos against a remote broker goes through the
        broker's own ArmFaults RPC / tools/chaos.py instead)."""
        from surge_tpu.testing.faults import FaultPlane

        op, _, rest = (request.name or "status").partition(":")
        log = self.engine.log
        try:
            if op == "arm":
                seed_str, _, spec = rest.partition(":")
                try:
                    seed = int(seed_str or 0)
                except ValueError:
                    seed, spec = 0, rest  # bare "arm:<plan>" (no seed)
                plane = FaultPlane.from_spec(spec, seed=seed,
                                             metrics=self.engine.metrics)
                current = getattr(log, "faults", None)
                if current is None:
                    if not hasattr(log, "faults"):
                        return pb.MetricsReply(metrics_json=json.dumps(
                            {"error": f"{type(log).__name__} has no fault "
                                      "hooks; arm the broker instead"}
                        ).encode())
                    log.faults = plane
                else:
                    current.arm(plane.rules, seed=plane.seed)
            elif op == "disarm":
                plane = getattr(log, "faults", None)
                if plane is not None:
                    plane.disarm()
            elif op != "status":
                return pb.MetricsReply(metrics_json=json.dumps(
                    {"error": f"unknown op {op!r}"}).encode())
            plane = getattr(log, "faults", None)
            stats = plane.stats() if plane is not None else {
                "rules": [], "injected": 0, "crashed": None}
            return pb.MetricsReply(metrics_json=json.dumps(stats).encode())
        except Exception as exc:  # noqa: BLE001 — operator gets it back
            return pb.MetricsReply(metrics_json=json.dumps(
                {"error": repr(exc)}).encode())

    async def ScanSegments(self, request, context) -> pb.MetricsReply:
        """Filter + grouped-aggregate scan over the engine's committed
        columnar segment (predicate pushdown, per-aggregate-id grouping,
        mesh-sharded on device). ``request.name`` is the ScanQuery JSON."""
        return await self._run_query(request, states=False)

    async def QueryStates(self, request, context) -> pb.MetricsReply:
        """Fold-then-filter state query over the committed segment (state
        column predicates + projection). ``request.name`` is the StateQuery
        JSON."""
        return await self._run_query(request, states=True)

    async def _run_query(self, request, states: bool) -> pb.MetricsReply:
        try:
            q = json.loads(request.name or "{}")
            result = await (self.engine.query_states(q) if states
                            else self.engine.query(q))
            cap = self.engine.config.get_int("surge.query.max-rows", 10_000)
            return pb.MetricsReply(metrics_json=json.dumps({
                "rows": result.rows(limit=cap),
                "num_aggregates": result.num_aggregates,
                "scanned_events": result.scanned_events,
                "matched_events": result.matched_events,
                "chunks": result.chunks,
                "truncated": result.num_aggregates > cap,
                "elapsed_ms": round(result.elapsed_s * 1000.0, 3),
            }).encode())
        except Exception as exc:  # noqa: BLE001 — operator gets the failure back
            return pb.MetricsReply(metrics_json=json.dumps(
                {"error": repr(exc)}).encode())

    async def QueryView(self, request, context) -> pb.MetricsReply:
        """Snapshot one materialized view (``request.name`` = view name), or
        — with an empty name — the per-view operator summary. The snapshot's
        numpy columns stay in-process; the RPC serves the ``rows`` form."""
        try:
            name = (request.name or "").strip()
            if not name or name == "{}":
                return pb.MetricsReply(metrics_json=json.dumps(
                    {"views": await self.engine.view_summary()}).encode())
            snap = await self.engine.query_view(name)
            payload = {k: v for k, v in snap.items() if k != "columns"}
            return pb.MetricsReply(metrics_json=json.dumps(payload).encode())
        except Exception as exc:  # noqa: BLE001 — operator gets the failure back
            return pb.MetricsReply(metrics_json=json.dumps(
                {"error": repr(exc)}).encode())

    async def SubscribeView(self, request, context):
        """Server-streaming changefeed: one MetricsReply frame per entry.
        ``request.name`` carries ``{"view": .., "from_version": ..}`` —
        ``from_version`` absent/null opens with a reconciling snapshot; a
        resume watermark the delta ring still covers replays exactly the
        missed deltas (no gap, no dup); anything older gets ONE reconciling
        snapshot. The stream ends when the engine stops or the view is
        unregistered (a terminal ``closed`` entry); clients end it any time
        by cancelling the call."""
        try:
            req = json.loads(request.name or "{}")
            sub = await self.engine.subscribe_view(
                req["view"], req.get("from_version"))
        except Exception as exc:  # noqa: BLE001 — operator gets the failure back
            yield pb.MetricsReply(metrics_json=json.dumps(
                {"error": repr(exc)}).encode())
            return
        try:
            async for entry in sub:
                yield pb.MetricsReply(
                    metrics_json=json.dumps(entry).encode())
                if entry.get("closed"):
                    return
        finally:
            self.engine.views.unsubscribe(sub)

    async def StopEngine(self, request, context) -> pb.ComponentReply:
        try:
            await self.engine.stop()
            return pb.ComponentReply(ok=True, detail="stopped")
        except Exception as exc:  # noqa: BLE001
            return pb.ComponentReply(ok=False, detail=repr(exc))

    # -- lifecycle -----------------------------------------------------------------------

    async def start(self) -> int:
        from surge_tpu.remote.security import add_secure_port

        self._server = grpc.aio.server()
        self._server.add_generic_rpc_handlers(
            (generic_handler(SERVICE, METHODS, self,
                             stream_methods=STREAM_METHODS),))
        self.bound_port = add_secure_port(
            self._server, f"{self._host}:{self._port}",
            getattr(self.engine, "config", None))
        await self._server.start()
        return self.bound_port

    async def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            await self._server.stop(grace)
            self._server = None


class AdminClient:
    """Typed operator client."""

    def __init__(self, channel: grpc.aio.Channel) -> None:
        self._calls = unary_callables(channel, SERVICE, METHODS)
        self._streams = stream_callables(channel, SERVICE, STREAM_METHODS)

    async def health(self) -> dict:
        reply = await self._calls["GetHealth"](pb.Empty())
        return json.loads(reply.tree_json)

    async def metrics(self) -> dict:
        reply = await self._calls["GetMetrics"](pb.Empty())
        return json.loads(reply.metrics_json)

    async def metrics_text(self) -> str:
        """OpenMetrics text payload (scrape-over-gRPC)."""
        reply = await self._calls["GetMetricsText"](pb.Empty())
        return reply.metrics_json.decode()

    async def flight_dump(self, last: Optional[int] = None) -> dict:
        """The engine's flight-recorder dump (merge-ready envelope: feed it
        to merge_dumps alongside broker dumps for one incident timeline)."""
        name = f"last:{last}" if last is not None else ""
        r = await self._calls["DumpFlight"](pb.ComponentRequest(name=name))
        return json.loads(r.metrics_json)

    async def trace_dump(self, last: Optional[int] = None) -> dict:
        """The engine's tail-kept trace-ring dump (merge-ready envelope:
        feed it to anatomy.assemble_traces alongside broker trace dumps for
        whole command traces). Raises RuntimeError on an untraced engine."""
        name = f"last:{last}" if last is not None else ""
        r = await self._calls["DumpTraces"](pb.ComponentRequest(name=name))
        payload = json.loads(r.metrics_json)
        if "error" in payload and "traces" not in payload:
            raise RuntimeError(payload["error"])
        return payload

    async def replay_ledger_dump(self, last: Optional[int] = None) -> dict:
        """The engine's refresh-round ledger dump (merge-ready envelope +
        roofline ``summary``: feed it to merge_dumps alongside flight dumps
        so fold rounds land on the incident timeline). Raises RuntimeError
        on an engine without the observatory."""
        name = f"last:{last}" if last is not None else ""
        r = await self._calls["DumpReplayLedger"](
            pb.ComponentRequest(name=name))
        payload = json.loads(r.metrics_json)
        if "error" in payload and "events" not in payload:
            raise RuntimeError(payload["error"])
        return payload

    async def components(self) -> list:
        return list((await self._calls["ListComponents"](pb.Empty())).names)

    async def restart_component(self, name: str) -> tuple[bool, str]:
        r = await self._calls["RestartComponent"](pb.ComponentRequest(name=name))
        return r.ok, r.detail

    async def compact_log(self, topic: str = "") -> list:
        """Force a compaction pass; returns per-partition stats dicts."""
        r = await self._calls["CompactLog"](pb.ComponentRequest(name=topic))
        return json.loads(r.metrics_json)

    async def write_checkpoint(self) -> tuple[bool, str]:
        r = await self._calls["WriteCheckpoint"](pb.Empty())
        return r.ok, r.detail

    async def start_saga(self, saga_id: str, definition: str,
                         ctx=()) -> dict:
        """Start (idempotently) a saga; returns its status ledger."""
        payload = json.dumps({"saga_id": saga_id, "definition": definition,
                              "ctx": list(ctx)})
        r = await self._calls["StartSaga"](pb.ComponentRequest(name=payload))
        out = json.loads(r.metrics_json)
        if "error" in out and "saga_id" not in out:
            raise RuntimeError(out["error"])
        return out

    async def saga_status(self, saga_id: str = "") -> dict:
        """One saga's ledger, or (empty id) the fleet summary with the
        reconciliation verdict."""
        r = await self._calls["SagaStatus"](pb.ComponentRequest(name=saga_id))
        out = json.loads(r.metrics_json)
        if "error" in out and "saga_id" not in out and "counts" not in out:
            raise RuntimeError(out["error"])
        return out

    async def audit_status(self) -> dict:
        """The consistency auditor's verdict (``ok``, unresolved ledger,
        last-round detail); raises when the auditor is not enabled."""
        r = await self._calls["AuditStatus"](pb.ComponentRequest())
        out = json.loads(r.metrics_json)
        if "error" in out and "ok" not in out:
            raise RuntimeError(out["error"])
        return out

    async def arm_faults(self, spec: str, seed: int = 0) -> dict:
        """Arm a named plan / JSON rules on the engine's in-process log;
        ``seed`` pins the plane's deterministic schedule for reproducibility
        (the broker-side twin takes it via TxnRequest.txn_seq)."""
        r = await self._calls["ArmFaults"](
            pb.ComponentRequest(name=f"arm:{seed}:{spec}"))
        return json.loads(r.metrics_json)

    async def disarm_faults(self) -> dict:
        r = await self._calls["ArmFaults"](pb.ComponentRequest(name="disarm"))
        return json.loads(r.metrics_json)

    async def fault_stats(self) -> dict:
        r = await self._calls["ArmFaults"](pb.ComponentRequest(name="status"))
        return json.loads(r.metrics_json)

    async def scan_segments(self, query: dict) -> dict:
        """Run a ScanQuery (json form) through the engine's scan engine over
        its committed columnar segment; returns the rows payload (capped at
        surge.query.max-rows, ``truncated`` flags the cap). Raises
        RuntimeError on a refused/failed query."""
        r = await self._calls["ScanSegments"](
            pb.ComponentRequest(name=json.dumps(query)))
        payload = json.loads(r.metrics_json)
        if "error" in payload and "rows" not in payload:
            raise RuntimeError(payload["error"])
        return payload

    async def query_states(self, query: dict) -> dict:
        """Run a StateQuery (json form): fold-then-filter over state columns
        with projection; same payload/caps as :meth:`scan_segments`."""
        r = await self._calls["QueryStates"](
            pb.ComponentRequest(name=json.dumps(query)))
        payload = json.loads(r.metrics_json)
        if "error" in payload and "rows" not in payload:
            raise RuntimeError(payload["error"])
        return payload

    async def query_view(self, name: str = "") -> dict:
        """Snapshot one materialized view (sorted keys + rows, top-k
        applied), or — with no name — the per-view operator summary
        (``{"views": [...]}``). Raises RuntimeError on a refused query; a
        DEGRADED view's payload (its ``error`` field set) is a legitimate
        answer and is returned, not raised."""
        r = await self._calls["QueryView"](pb.ComponentRequest(name=name))
        payload = json.loads(r.metrics_json)
        if "error" in payload and "view" not in payload \
                and "views" not in payload:
            raise RuntimeError(payload["error"])
        return payload

    def subscribe_view(self, view: str, from_version: Optional[int] = None):
        """Open a changefeed: an async iterator of entry dicts (first a
        reconciling snapshot or the exactly-missed deltas, then live
        per-round deltas). Ends on a terminal ``closed`` entry; end it early
        by breaking out (the call is cancelled). Raises RuntimeError when
        the subscription is refused (unknown view, no plane)."""
        call = self._streams["SubscribeView"](pb.ComponentRequest(
            name=json.dumps({"view": view, "from_version": from_version})))

        async def entries():
            try:
                async for r in call:
                    payload = json.loads(r.metrics_json)
                    if "error" in payload and "view" not in payload:
                        raise RuntimeError(payload["error"])
                    yield payload
                    if payload.get("closed"):
                        return
            finally:
                call.cancel()

        return entries()

    async def stop_engine(self) -> tuple[bool, str]:
        r = await self._calls["StopEngine"](pb.Empty())
        return r.ok, r.detail
