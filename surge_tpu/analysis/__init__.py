"""surgelint — repo-native static analysis for concurrency, config, and
catalog invariants (docs/static-analysis.md).

Entry points: ``tools/surgelint.py`` (CLI), :func:`run_paths` (library,
what tests/test_lint.py drives), :func:`all_rules` (the registry).
"""

from surge_tpu.analysis.core import (
    DEFAULT_TARGETS,
    Finding,
    ModuleContext,
    RepoContext,
    Report,
    Rule,
    all_rules,
    collect_files,
    load_baseline,
    run_paths,
    write_baseline,
)
from surge_tpu.analysis.reporters import render_json, render_text

__all__ = [
    "DEFAULT_TARGETS",
    "Finding",
    "ModuleContext",
    "RepoContext",
    "Report",
    "Rule",
    "all_rules",
    "collect_files",
    "load_baseline",
    "render_json",
    "render_text",
    "run_paths",
    "write_baseline",
]
