"""surgelint core — rule registry, per-file visitor pipeline, pragmas, baseline.

The framework half of ``surge_tpu.analysis`` (the rules live in
``surge_tpu.analysis.rules``): repo-native static analysis distilled from this
repo's actual bug history — awaits under threading locks, blocking syscalls on
the event loop, the py3.10 ``asyncio.wait_for`` cancellation swallow, orphaned
tasks, config/metric registry drift, jit impurity and proto route drift
(docs/static-analysis.md catalogs each rule and the incident it encodes).

Two rule shapes:

- **module rules** (`Rule.check_module`) get a parsed :class:`ModuleContext`
  per file and emit findings from its AST;
- **repo rules** (`Rule.check_repo`, ``repo_scope=True``) get a
  :class:`RepoContext` holding EVERY canonical target module plus the
  cross-file registries (config defaults, docs texts, golden metric families)
  and emit findings anywhere — they always run over the full canonical
  surface and are never path-filtered, so a ``--changed`` run cannot miss a
  drift that anchors in a file the user didn't touch.

Suppression is per line: ``# surgelint: disable=<rule>[,<rule>]  # <why>``
on the finding's line. A justification comment is REQUIRED — a bare disable
is itself reported (``pragma-justification``). Suppressions are tallied in
the report so hand-waving accumulates visibly. Findings that predate the
suite live in the checked-in baseline (``.surgelint-baseline.json``), keyed
by (rule, path, stripped source line) so line drift does not invalidate it.
"""

from __future__ import annotations

import ast
import json
import os
import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

__all__ = [
    "Finding",
    "Rule",
    "ModuleContext",
    "RepoContext",
    "Report",
    "register",
    "all_rules",
    "run_paths",
    "collect_files",
    "load_baseline",
    "write_baseline",
    "baseline_key",
    "DEFAULT_TARGETS",
    "PRAGMA_RE",
]

#: the canonical lint surface (tier-1 runs the suite over exactly this set)
DEFAULT_TARGETS = ("surge_tpu", "tools", "bench.py")

#: generated / vendored files never scanned
EXCLUDED_BASENAMES = frozenset({"log_service_pb2.py"})
EXCLUDED_DIRS = frozenset({"__pycache__", ".git", "lint_fixtures"})

PRAGMA_RE = re.compile(
    r"#\s*surgelint:\s*disable=([A-Za-z0-9_,-]+)\s*(?:#\s*(\S.*))?$")


@dataclass
class Finding:
    """One defect at one location. ``snippet`` (the stripped source line) is
    the line-drift-proof half of the baseline key."""

    rule: str
    path: str
    line: int
    message: str
    snippet: str = ""
    suppressed: bool = False
    justification: str = ""

    def sort_key(self) -> Tuple[str, int, str]:
        return (self.path, self.line, self.rule)

    def as_dict(self) -> dict:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "message": self.message}
        if self.suppressed:
            d["suppressed"] = True
            d["justification"] = self.justification
        return d


class Rule:
    """Base rule. Subclasses set ``id``/``summary`` and implement one of
    ``check_module`` (per-file AST) or ``check_repo`` (cross-file)."""

    id: str = ""
    summary: str = ""
    #: repo rules aggregate over every canonical target before emitting
    repo_scope: bool = False

    def check_module(self, ctx: "ModuleContext") -> Iterator[Finding]:
        return iter(())

    def check_repo(self, ctx: "RepoContext") -> Iterator[Finding]:
        return iter(())

    # -- shared helper -------------------------------------------------------------

    def finding(self, ctx: "ModuleContext", node_or_line, message: str) -> Finding:
        line = getattr(node_or_line, "lineno", node_or_line)
        return Finding(rule=self.id, path=ctx.rel_path, line=line,
                       message=message, snippet=ctx.line_text(line))


_REGISTRY: Dict[str, Rule] = {}


def register(rule_cls: type) -> type:
    """Class decorator adding a rule to the global registry (idempotent —
    re-imports under pytest must not duplicate)."""
    rule = rule_cls()
    if not rule.id:
        raise ValueError(f"rule {rule_cls.__name__} has no id")
    _REGISTRY[rule.id] = rule
    return rule_cls


def all_rules() -> Dict[str, Rule]:
    import surge_tpu.analysis.rules  # noqa: F401 — populates the registry
    return dict(_REGISTRY)


# -- module / repo contexts --------------------------------------------------------


class ModuleContext:
    """One parsed file plus the lookups every rule wants: physical lines,
    pragma map, dotted-name rendering, scope-aware walks."""

    def __init__(self, path: str, rel_path: str, source: str,
                 tree: ast.AST) -> None:
        self.path = path
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._pragmas: Optional[Dict[int, Tuple[List[str], str]]] = None

    @classmethod
    def parse(cls, path: str, repo_root: str) -> "ModuleContext":
        with open(path, encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
        rel = os.path.relpath(path, repo_root)
        return cls(path, rel, source, tree)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    @property
    def pragmas(self) -> Dict[int, Tuple[List[str], str]]:
        """line -> (disabled rule ids, justification)."""
        if self._pragmas is None:
            self._pragmas = {}
            for i, text in enumerate(self.lines, start=1):
                m = PRAGMA_RE.search(text)
                if m:
                    rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
                    self._pragmas[i] = (rules, (m.group(2) or "").strip())
        return self._pragmas

    # -- AST helpers ----------------------------------------------------------------

    @staticmethod
    def dotted(node: ast.AST) -> Optional[str]:
        """Render a Name/Attribute chain as ``a.b.c`` (None for anything else)."""
        parts: List[str] = []
        while isinstance(node, ast.Attribute):
            parts.append(node.attr)
            node = node.value
        if isinstance(node, ast.Name):
            parts.append(node.id)
            return ".".join(reversed(parts))
        return None

    @staticmethod
    def walk_scope(node: ast.AST) -> Iterator[ast.AST]:
        """Walk descendants WITHOUT entering nested function/lambda/class
        scopes (their bodies execute elsewhere — an executor thunk's blocking
        call is the point of the thunk, not an event-loop stall)."""
        stack = list(ast.iter_child_nodes(node))
        while stack:
            child = stack.pop()
            yield child
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(child))

    def functions(self) -> Iterator[ast.AST]:
        """Every function def in the file, any nesting depth."""
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    def async_functions(self) -> Iterator[ast.AsyncFunctionDef]:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.AsyncFunctionDef):
                yield node


class RepoContext:
    """Every canonical target module parsed once, plus lazy cross-file
    registries. Repo rules read these; the runner restricts their findings to
    the user-requested path set."""

    def __init__(self, repo_root: str, modules: List[ModuleContext]) -> None:
        self.repo_root = repo_root
        self.modules = modules
        self._docs: Dict[str, str] = {}

    def doc_text(self, rel: str) -> str:
        if rel not in self._docs:
            path = os.path.join(self.repo_root, rel)
            try:
                with open(path, encoding="utf-8") as f:
                    self._docs[rel] = f.read()
            except OSError:
                self._docs[rel] = ""
        return self._docs[rel]

    def module(self, rel_path: str) -> Optional[ModuleContext]:
        for m in self.modules:
            if m.rel_path == rel_path:
                return m
        return None


# -- file collection ---------------------------------------------------------------


def collect_files(targets: Sequence[str], repo_root: str) -> List[str]:
    """Expand dirs to .py files, skipping generated/vendored ones. A
    nonexistent target raises — a typo'd path in a CI hook must not lint
    nothing and report clean forever."""
    out: List[str] = []
    for target in targets:
        path = target if os.path.isabs(target) else os.path.join(repo_root, target)
        if not os.path.exists(path):
            raise FileNotFoundError(f"lint target does not exist: {target}")
        if os.path.isfile(path):
            if path.endswith(".py") and os.path.basename(path) not in EXCLUDED_BASENAMES:
                out.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = sorted(d for d in dirnames if d not in EXCLUDED_DIRS)
            for name in sorted(filenames):
                if name.endswith(".py") and name not in EXCLUDED_BASENAMES:
                    out.append(os.path.join(dirpath, name))
    seen, uniq = set(), []
    for p in out:
        rp = os.path.realpath(p)
        if rp not in seen:
            seen.add(rp)
            uniq.append(p)
    return uniq


# -- baseline ----------------------------------------------------------------------


def baseline_key(f: Finding) -> Tuple[str, str, str]:
    return (f.rule, f.path, f.snippet)


def load_baseline(path: str) -> Counter:
    """Multiset of (rule, path, snippet) keys the repo has accepted."""
    try:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
    except OSError:
        return Counter()
    return Counter((e["rule"], e["path"], e.get("snippet", ""))
                   for e in data.get("findings", []))


def write_baseline(path: str, findings: Iterable[Finding]) -> None:
    entries = [{"rule": f.rule, "path": f.path, "snippet": f.snippet,
                "message": f.message}
               for f in sorted(findings, key=Finding.sort_key)]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "findings": entries}, fh, indent=1)
        fh.write("\n")


# -- runner ------------------------------------------------------------------------


@dataclass
class Report:
    """One run's outcome: what fires, what was hushed, what predates us."""

    findings: List[Finding] = field(default_factory=list)      # actionable
    suppressed: List[Finding] = field(default_factory=list)    # pragma'd, justified
    baselined: List[Finding] = field(default_factory=list)     # accepted debt
    errors: List[str] = field(default_factory=list)            # unparsable files
    files_scanned: int = 0
    rules_run: List[str] = field(default_factory=list)

    @property
    def exit_code(self) -> int:
        return 1 if (self.findings or self.errors) else 0

    def tally(self) -> Dict[str, int]:
        c: Counter = Counter(f.rule for f in self.findings)
        return dict(sorted(c.items()))

    def suppression_tally(self) -> Dict[str, int]:
        c: Counter = Counter(f.rule for f in self.suppressed)
        return dict(sorted(c.items()))


def _apply_pragmas(findings: List[Finding], ctx_by_rel: Dict[str, ModuleContext],
                   report: Report) -> List[Finding]:
    """Split pragma-disabled findings out; a disable without a justification
    comment is converted into a ``pragma-justification`` finding so silent
    hushing is impossible."""
    kept: List[Finding] = []
    for f in findings:
        ctx = ctx_by_rel.get(f.path)
        pragma = ctx.pragmas.get(f.line) if ctx else None
        if pragma and f.rule in pragma[0]:
            if not pragma[1]:
                kept.append(Finding(
                    rule="pragma-justification", path=f.path, line=f.line,
                    message=(f"disable={f.rule} needs an inline justification "
                             "(`# surgelint: disable=... # <why>`)"),
                    snippet=f.snippet))
            else:
                f.suppressed = True
                f.justification = pragma[1]
                report.suppressed.append(f)
            continue
        kept.append(f)
    return kept


def run_paths(paths: Sequence[str], repo_root: str,
              select: Optional[Sequence[str]] = None,
              baseline_path: Optional[str] = None) -> Report:
    """Run the suite over ``paths`` (files or directories, repo-root
    relative or absolute). Repo-scope rules always run over the canonical
    DEFAULT_TARGETS, unfiltered — cross-file invariants hold or fail
    repo-wide regardless of the requested path set."""
    report = Report()
    rules = all_rules()
    if select:
        unknown = sorted(set(select) - set(rules))
        if unknown:
            raise ValueError(f"unknown rule id(s): {', '.join(unknown)}")
        rules = {rid: r for rid, r in rules.items() if rid in select}
    report.rules_run = sorted(rules)

    requested_files = collect_files(paths, repo_root)
    ctx_by_rel: Dict[str, ModuleContext] = {}
    contexts: List[ModuleContext] = []
    for path in requested_files:
        try:
            ctx = ModuleContext.parse(path, repo_root)
        except (SyntaxError, UnicodeDecodeError, OSError) as exc:
            report.errors.append(f"{os.path.relpath(path, repo_root)}: {exc}")
            continue
        contexts.append(ctx)
        ctx_by_rel[ctx.rel_path] = ctx
    report.files_scanned = len(contexts)

    raw: List[Finding] = []
    module_rules = [r for r in rules.values() if not r.repo_scope]
    for ctx in contexts:
        for rule in module_rules:
            raw.extend(rule.check_module(ctx))

    repo_rules = [r for r in rules.values() if r.repo_scope]
    if repo_rules:
        # aggregate over the FULL canonical surface so cross-file invariants
        # (key read in a file outside `paths`) hold under --changed runs
        canon_files = collect_files(DEFAULT_TARGETS, repo_root)
        canon_ctx: List[ModuleContext] = []
        for path in canon_files:
            rel = os.path.relpath(path, repo_root)
            if rel in ctx_by_rel:
                canon_ctx.append(ctx_by_rel[rel])
                continue
            try:
                ctx = ModuleContext.parse(path, repo_root)
            except (SyntaxError, UnicodeDecodeError, OSError):
                continue  # already reported if requested; else not our file
            canon_ctx.append(ctx)
            ctx_by_rel[ctx.rel_path] = ctx
        repo_ctx = RepoContext(repo_root, canon_ctx)
        for rule in repo_rules:
            # repo-rule findings are NEVER path-filtered: a cross-file drift
            # often anchors in a file the user didn't touch (the DEFAULTS
            # row, the proto file) — dropping it there would make a
            # --changed run lie about the invariant it exists to guard
            raw.extend(rule.check_repo(repo_ctx))

    raw = _apply_pragmas(raw, ctx_by_rel, report)

    baseline = load_baseline(baseline_path) if baseline_path else Counter()
    remaining = Counter(baseline)
    kept: List[Finding] = []
    for f in sorted(raw, key=Finding.sort_key):
        key = baseline_key(f)
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            report.baselined.append(f)
        else:
            kept.append(f)
    report.findings = kept
    return report
