"""surgelint reporters — human text and machine JSON renderings of a Report."""

from __future__ import annotations

import json
from typing import List

from surge_tpu.analysis.core import Report

__all__ = ["render_text", "render_json"]


def render_text(report: Report, verbose: bool = False) -> str:
    out: List[str] = []
    for f in report.findings:
        out.append(f"{f.path}:{f.line}: [{f.rule}] {f.message}")
        if f.snippet:
            out.append(f"    {f.snippet}")
    if report.errors:
        out.append("")
        for e in report.errors:
            out.append(f"error: {e}")
    out.append("")
    tally = report.tally()
    if tally:
        out.append("findings by rule: "
                   + ", ".join(f"{r}={n}" for r, n in tally.items()))
    stally = report.suppression_tally()
    if stally:
        out.append("suppressed (justified pragmas): "
                   + ", ".join(f"{r}={n}" for r, n in stally.items()))
        if verbose:
            for f in report.suppressed:
                out.append(f"  {f.path}:{f.line}: [{f.rule}] — {f.justification}")
    if report.baselined:
        out.append(f"baselined: {len(report.baselined)} accepted finding(s) "
                   "(.surgelint-baseline.json)")
    status = "FAILED" if report.exit_code else "clean"
    out.append(f"surgelint: {status} — {len(report.findings)} finding(s), "
               f"{len(report.suppressed)} suppressed, "
               f"{len(report.baselined)} baselined, "
               f"{report.files_scanned} file(s), "
               f"{len(report.rules_run)} rule(s)")
    return "\n".join(out)


def render_json(report: Report) -> str:
    return json.dumps({
        "findings": [f.as_dict() for f in report.findings],
        "suppressed": [f.as_dict() for f in report.suppressed],
        "baselined": [f.as_dict() for f in report.baselined],
        "errors": report.errors,
        "files_scanned": report.files_scanned,
        "rules_run": report.rules_run,
        "tally": report.tally(),
        "suppression_tally": report.suppression_tally(),
        "exit_code": report.exit_code,
    }, indent=1)
