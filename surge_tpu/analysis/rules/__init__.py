"""surgelint rule modules — importing this package populates the registry."""

from surge_tpu.analysis.rules import concurrency  # noqa: F401
from surge_tpu.analysis.rules import hotpath  # noqa: F401
from surge_tpu.analysis.rules import jit  # noqa: F401
from surge_tpu.analysis.rules import proto  # noqa: F401
from surge_tpu.analysis.rules import registries  # noqa: F401
from surge_tpu.analysis.rules import tracing  # noqa: F401
