"""Concurrency rules — the lock/await/cancellation defect classes this repo
has actually shipped (and hand-caught in review) since PR 3.

Each rule's docstring names the historical incident it encodes; the fixture
corpus under tests/lint_fixtures/ pins the exact shapes.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set

from surge_tpu.analysis.core import Finding, ModuleContext, Rule, register

_THREADING_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}


def _leaf_name(node: ast.AST) -> Optional[str]:
    """`self._role_lock` -> `_role_lock`; bare `lock` -> `lock`."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _threading_lock_names(ctx: ModuleContext) -> Set[str]:
    """Leaf names bound (anywhere in the module) to a threading Lock/RLock/
    Condition constructor call. Matching With items by leaf name deliberately
    crosses class boundaries: `with other._lock:` around an await is exactly
    as deadlock-prone as `with self._lock:`."""
    names: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not (isinstance(value, ast.Call)
                and ctx.dotted(value.func) in _THREADING_LOCK_CTORS):
            continue
        # only count the bare-name ctors when threading itself is imported —
        # `Condition()` from asyncio would be a false positive
        if isinstance(value.func, ast.Name) and "import threading" not in ctx.source \
                and "from threading import" not in ctx.source:
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            leaf = _leaf_name(t)
            if leaf:
                names.add(leaf)
    return names


@register
class AwaitUnderLock(Rule):
    """An ``await`` lexically inside a ``with <threading lock>`` body.

    History: the PR-3 fsync-inside-producer-lock stall (replication acks had
    to move OUTSIDE the lock so the pipelined window overlaps fsync) and the
    PR-7 review round that re-unified Transact's fence check + in-flight
    increment under ONE role-lock hold. A threading lock held across an await
    blocks every OTHER event-loop task that needs it — the loop itself can
    deadlock if the lock's holder is resumed by a callback the lock blocks.
    """

    id = "await-under-lock"
    summary = "await inside a `with threading.Lock/RLock/Condition` body"

    def check_module(self, ctx: ModuleContext) -> Iterator[Finding]:
        lock_names = _threading_lock_names(ctx)
        if not lock_names:
            return
        for fn in ctx.async_functions():
            yield from self._scan(ctx, fn, lock_names, held=None)

    def _scan(self, ctx: ModuleContext, node: ast.AST, lock_names: Set[str],
              held: Optional[str]) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue  # separate execution context
            now_held = held
            if isinstance(child, ast.With):
                for item in child.items:
                    expr = item.context_expr
                    # unwrap `with lock:` vs `with lock_factory():`
                    leaf = _leaf_name(expr)
                    if leaf in lock_names:
                        now_held = leaf
            if isinstance(child, ast.Await) and now_held:
                yield self.finding(
                    ctx, child,
                    f"await while holding threading lock `{now_held}` — the "
                    "event loop (and every task needing the lock) stalls until "
                    "this resumes; move the await outside the lock hold")
                continue
            yield from self._scan(ctx, child, lock_names, now_held)


_BLOCKING_CALLS: Dict[str, str] = {
    "time.sleep": "use `await asyncio.sleep(...)`",
    "os.fsync": "dispatch through the log's group-sync worker or an executor",
    "os.fdatasync": "dispatch through an executor",
    "subprocess.run": "use `asyncio.create_subprocess_exec`",
    "subprocess.check_output": "use `asyncio.create_subprocess_exec`",
    "subprocess.check_call": "use `asyncio.create_subprocess_exec`",
    "grpc.insecure_channel": "use `grpc.aio.insecure_channel` (the sync "
                             "channel's RPCs block the loop)",
    "grpc.secure_channel": "use `grpc.aio.secure_channel`",
}


@register
class BlockingInAsync(Rule):
    """A blocking syscall on the event loop: ``time.sleep``/``os.fsync``/sync
    file I/O/sync gRPC channels/executor ``Future.result()`` directly inside
    an ``async def`` (thunks handed to ``run_in_executor``/``to_thread`` are
    nested defs or lambdas and are exempt by scope).

    History: the PR-3 WAL rebuild existed precisely because per-commit
    ``os.fsync`` on the loop serialized every committer behind 1.3–45 ms of
    9p fsync; the event-loop prober (``surge.event-loop-prober.*``) was built
    to catch survivors of this class at runtime — this rule catches them at
    review time.
    """

    id = "blocking-in-async"
    summary = "blocking call (sleep/fsync/file I/O/sync gRPC/Future.result) in async def"

    def check_module(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ctx.async_functions():
            submit_vars = self._executor_submit_vars(fn)
            for node in ctx.walk_scope(fn):
                if not isinstance(node, ast.Call):
                    continue
                dotted = ctx.dotted(node.func)
                if dotted in _BLOCKING_CALLS:
                    yield self.finding(
                        ctx, node,
                        f"`{dotted}(...)` blocks the event loop inside "
                        f"`async def {fn.name}` — {_BLOCKING_CALLS[dotted]}")
                elif isinstance(node.func, ast.Name) and node.func.id == "open":
                    yield self.finding(
                        ctx, node,
                        f"sync file I/O (`open`) inside `async def {fn.name}` "
                        "blocks the event loop — read/write via "
                        "`loop.run_in_executor` (9p fsync on this class of "
                        "host runs 1.3-45ms)")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr == "result" and not node.args
                      and self._is_executor_future(node.func.value, submit_vars)):
                    yield self.finding(
                        ctx, node,
                        f"`Future.result()` on an executor future inside "
                        f"`async def {fn.name}` parks the loop until the "
                        "worker finishes — await "
                        "`asyncio.wrap_future(...)` instead")

    @staticmethod
    def _executor_submit_vars(fn: ast.AST) -> Set[str]:
        """Names assigned from `<executor>.submit(...)` in this function."""
        out: Set[str] = set()
        for node in ModuleContext.walk_scope(fn):
            if (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr == "submit"):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    @staticmethod
    def _is_executor_future(receiver: ast.AST, submit_vars: Set[str]) -> bool:
        """`pool.submit(...).result()` or `fut.result()` where fut came from
        a `.submit(...)` in the same function. asyncio futures' `.result()`
        is non-blocking, so a bare receiver is NOT flagged."""
        if isinstance(receiver, ast.Call) and isinstance(receiver.func, ast.Attribute) \
                and receiver.func.attr == "submit":
            return True
        return isinstance(receiver, ast.Name) and receiver.id in submit_vars


@register
class WaitforCancellationSwallow(Rule):
    """Bare ``asyncio.wait_for`` in a retry/poll loop (or on a task) without
    the shield + re-cancel pattern.

    History: the tier-1 cluster-test hang that silently truncated the suite
    for two PRs — py3.10's ``wait_for`` swallows a cancellation that races a
    timeout or a completing inner future (bpo-37658 family), so a loop built
    on it keeps running after ``task.cancel()`` and the stop chain hangs
    forever. ``BackgroundTask.stop`` re-cancels on a deadline loop over
    ``wait_for(asyncio.shield(task), ...)``; the publisher's ``_Signal`` and
    the entity's ``_Mailbox`` exist to avoid the shape entirely. Inside a
    loop, wrap the awaitable in ``asyncio.shield`` and re-cancel on timeout
    (common.py:BackgroundTask.stop), or use a ``_Mailbox``/``_Signal``.
    """

    id = "waitfor-cancellation-swallow"
    summary = "asyncio.wait_for in a loop (or on a task) without shield+re-cancel"

    _WAITFOR = {"asyncio.wait_for", "wait_for"}
    _TASK_CTORS = {"asyncio.create_task", "asyncio.ensure_future",
                   "create_task", "ensure_future"}

    def check_module(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ctx.async_functions():
            task_vars = self._task_vars(ctx, fn)
            yield from self._scan(ctx, fn, task_vars, in_loop=False)

    def _task_vars(self, ctx: ModuleContext, fn: ast.AST) -> Set[str]:
        out: Set[str] = set()
        for node in ctx.walk_scope(fn):
            if (isinstance(node, ast.Assign) and isinstance(node.value, ast.Call)
                    and ctx.dotted(node.value.func) in self._TASK_CTORS):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        return out

    def _scan(self, ctx: ModuleContext, node: ast.AST, task_vars: Set[str],
              in_loop: bool) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            child_in_loop = in_loop or isinstance(child, (ast.While, ast.For,
                                                          ast.AsyncFor))
            if isinstance(child, ast.Call) and ctx.dotted(child.func) in self._WAITFOR \
                    and child.args:
                inner = child.args[0]
                shielded = (isinstance(inner, ast.Call)
                            and ctx.dotted(inner.func) in ("asyncio.shield", "shield"))
                on_task = isinstance(inner, ast.Name) and inner.id in task_vars
                if not shielded and (child_in_loop or on_task):
                    where = ("on a task" if on_task and not child_in_loop
                             else "in a loop")
                    yield self.finding(
                        ctx, child,
                        f"bare `asyncio.wait_for` {where}: py3.10 can swallow "
                        "a cancellation racing the timeout (bpo-37658) and the "
                        "loop outlives `task.cancel()` — wrap the awaitable in "
                        "`asyncio.shield` and re-cancel on timeout "
                        "(BackgroundTask.stop), or use a _Mailbox/_Signal")
                    continue  # don't re-flag the inner call
            yield from self._scan(ctx, child, task_vars, child_in_loop)


@register
class OrphanTask(Rule):
    """``asyncio.create_task`` / ``ensure_future`` whose result is dropped on
    the floor — nothing retains, awaits, or supervises it.

    History: every supervised loop in this repo runs under
    ``BackgroundTask`` (common.py) precisely because a dropped task handle
    (a) can be garbage-collected mid-flight, (b) swallows its exception until
    interpreter exit, and (c) cannot be stopped — the health supervisor's
    restart contract needs the handle. Retain the task (attr/list), await it,
    or wrap the loop in ``BackgroundTask``; genuine fire-and-forget teardown
    needs a justified pragma.
    """

    id = "orphan-task"
    summary = "create_task/ensure_future result dropped (not retained or supervised)"

    _CTORS = {"asyncio.create_task", "asyncio.ensure_future",
              "create_task", "ensure_future"}

    def check_module(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            dotted = ctx.dotted(call.func)
            if dotted not in self._CTORS:
                # also catch `loop.create_task(...)` / `get_event_loop().create_task`
                if not (isinstance(call.func, ast.Attribute)
                        and call.func.attr in ("create_task", "ensure_future")):
                    continue
            yield self.finding(
                ctx, node,
                "task handle dropped: the task can be GC'd mid-flight and its "
                "exception is silently swallowed — retain it, await it, or "
                "supervise it with BackgroundTask")
