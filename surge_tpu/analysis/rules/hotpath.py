"""Hot-path asyncio rule — per-item event-loop round-trips on fast paths.

Encodes what ISSUE 12's descent removed from the engine command lane: the
per-command ``asyncio.wait_for`` wrapper task (replaced by the bare timer
wait :func:`surge_tpu.common.wait_future`), per-record awaits inside loops,
and per-call ``asyncio.Future`` construction in per-record loops. Modules
opt in by carrying a ``surgelint: fast-path-module`` marker comment — the
rule is about paths where "one more loop hop per command" is a measured
regression (BENCH_NOTES rounds 6/9), not about background loops, so it
stays opt-in.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from surge_tpu.analysis.core import Finding, ModuleContext, Rule, register

#: module opt-in marker (a comment anywhere in the file)
MARKER = "surgelint: fast-path-module"

#: loop iterables that are NOT per-item data walks (bounded retry ladders)
_EXEMPT_ITER_CALLS = {"range", "enumerate"}


def _per_item_loop(node: ast.AST) -> bool:
    """A ``for`` over data (not a bounded ``range()`` retry ladder)."""
    if not isinstance(node, (ast.For, ast.AsyncFor)):
        return False
    it = node.iter
    if isinstance(it, ast.Call):
        fn = it.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        if name in _EXEMPT_ITER_CALLS:
            return False
    return True


@register
class HotPathAsyncio(Rule):
    """Per-item event-loop round-trips in a fast-path-annotated module.

    History: PR 10's paired ladder showed the inproc rungs (1.02–1.04×)
    were capped by the per-command Python AROUND the native core — the
    publisher/asyncio machinery. ISSUE 12 removed exactly these shapes:

    - ``asyncio.wait_for(...)`` — a wrapper task + waiter future per call;
      use ``common.wait_future`` (bare futures) or
      ``common.cancel_safe_wait_for`` (coroutines) instead;
    - ``await`` inside a per-record ``for`` loop — one loop hop per item
      where one batched await would do;
    - ``asyncio.Future()`` / ``loop.create_future()`` inside a per-record
      loop — per-call future machinery where a batch-level future would do
      (the publisher's direct lane shares ONE ack per group commit).

    Opt-in via a ``surgelint: fast-path-module`` comment; slow paths inside
    such a module suppress per line with a justified pragma.
    """

    id = "hot-path-asyncio"
    summary = ("per-item event-loop round-trip (wait_for / await-in-loop / "
               "per-call Future) in a fast-path module")

    def check_module(self, ctx: ModuleContext) -> Iterator[Finding]:
        if MARKER not in ctx.source:
            return
        for fn in ctx.async_functions():
            yield from self._scan(ctx, fn, in_loop=False)
        # asyncio.wait_for is a finding even outside async defs (a sync
        # helper handing back the coroutine still builds the wrapper task)
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and ctx.dotted(node.func) == "asyncio.wait_for"):
                yield self.finding(
                    ctx, node,
                    "asyncio.wait_for builds a wrapper task + waiter per "
                    "call — use common.wait_future (bare futures) or "
                    "common.cancel_safe_wait_for (coroutines) on this "
                    "fast path")

    def _scan(self, ctx: ModuleContext, node: ast.AST,
              in_loop: bool) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue  # separate execution context
            now_in = in_loop or _per_item_loop(child)
            if in_loop or now_in:
                if isinstance(child, ast.Await) and now_in:
                    yield self.finding(
                        ctx, child,
                        "await inside a per-item loop: one event-loop hop "
                        "per record — batch the await (one per group) or "
                        "move the loop off the fast path")
                    continue
                if isinstance(child, ast.Call) and now_in:
                    name = ctx.dotted(child.func) or ""
                    if (name == "asyncio.Future"
                            or name.endswith(".create_future")):
                        yield self.finding(
                            ctx, child,
                            "per-item asyncio.Future construction: use a "
                            "batch-level future resolved once per group "
                            "(the direct command lane's shared ack shape)")
            yield from self._scan(ctx, child, now_in)
