"""jit-purity — Python side effects inside functions staged through
``jax.jit`` / ``shard_map`` / the replay fold builders.

A staged function's Python body runs ONCE at trace time: a ``print`` fires
once then never again, a wall-clock read bakes a constant timestamp into the
compiled program, and mutation of closed-over host state (``stats.append``,
``cache[k] = …``) happens at trace time only — silently wrong on every
subsequent cached-compilation call. The replay engine's fold builders
(``fold_resident_slab``, ``_make_densify``, the ``replay_*`` programs) are
all built this way, so the ROADMAP item-3 push of the hot path off the GIL
multiplies the blast radius of one impure fold.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from surge_tpu.analysis.core import Finding, ModuleContext, Rule, register

_STAGERS = frozenset({"jax.jit", "jit", "shard_map", "jax.shard_map",
                      "pjit", "jax.pjit"})
_CLOCK_CALLS = frozenset({"time.time", "time.perf_counter", "time.monotonic",
                          "time.time_ns", "time.perf_counter_ns",
                          "datetime.now", "datetime.datetime.now",
                          "datetime.utcnow", "datetime.datetime.utcnow"})
_MUTATING_METHODS = frozenset({"append", "extend", "insert", "update",
                               "setdefault", "add", "discard", "remove",
                               "pop", "popitem", "clear"})


@register
class JitPurity(Rule):
    id = "jit-purity"
    summary = "Python side effect (print/clock/closed-over mutation) in a staged fn"

    def check_module(self, ctx: ModuleContext) -> Iterator[Finding]:
        module_names = self._module_level_names(ctx)
        # decorator-staged functions
        for fn in ctx.functions():
            for dec in fn.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                dotted = ctx.dotted(target)
                if dotted in _STAGERS or (
                        isinstance(dec, ast.Call) and dec.args
                        and ctx.dotted(dec.args[0]) in _STAGERS):
                    yield from self._check_staged(ctx, fn, module_names)
                    break
        # call-staged functions: jit(f) / shard_map(f, ...) where f is a
        # def in the same lexical body
        for scope in self._scopes(ctx):
            local_defs = {n.name: n for n in ctx.walk_scope(scope)
                          if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
            for node in ast.walk(scope):
                if not (isinstance(node, ast.Call)
                        and ctx.dotted(node.func) in _STAGERS and node.args):
                    continue
                staged = node.args[0]
                fn = None
                if isinstance(staged, ast.Name):
                    fn = local_defs.get(staged.id)
                if fn is not None:
                    yield from self._check_staged(ctx, fn, module_names)
                elif isinstance(staged, ast.Lambda):
                    yield from self._check_staged(ctx, staged, module_names)

    def _scopes(self, ctx: ModuleContext):
        yield ctx.tree
        yield from ctx.functions()

    def _check_staged(self, ctx: ModuleContext, fn: ast.AST,
                      module_names: Set[str]) -> Iterator[Finding]:
        local = self._local_names(fn)
        name = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                dotted = ctx.dotted(node.func)
                if isinstance(node.func, ast.Name) and node.func.id == "print":
                    yield self.finding(
                        ctx, node,
                        f"`print` inside staged fn `{name}` fires at trace "
                        "time only — use jax.debug.print if it must survive "
                        "compilation")
                elif dotted in _CLOCK_CALLS:
                    yield self.finding(
                        ctx, node,
                        f"wall-clock read inside staged fn `{name}` bakes a "
                        "trace-time constant into the compiled program — pass "
                        "timestamps in as arguments")
                elif (isinstance(node.func, ast.Attribute)
                      and node.func.attr in _MUTATING_METHODS):
                    base = self._base_name(node.func.value)
                    if base and base not in local and base not in module_names:
                        yield self.finding(
                            ctx, node,
                            f"`{base}.{node.func.attr}(...)` mutates "
                            f"closed-over host state inside staged fn "
                            f"`{name}` — it runs at trace time only (cached "
                            "calls skip it); return the value instead")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        base = self._base_name(t.value)
                        if base and base not in local and base not in module_names:
                            yield self.finding(
                                ctx, node,
                                f"subscript assignment into closed-over "
                                f"`{base}` inside staged fn `{name}` happens "
                                "at trace time only — cached compilations "
                                "skip it")

    @staticmethod
    def _base_name(node: ast.AST) -> Optional[str]:
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    @staticmethod
    def _local_names(fn: ast.AST) -> Set[str]:
        """Params + names assigned anywhere inside the staged fn (its own
        state is fair game — purity is about what it closes over)."""
        local: Set[str] = set()
        args = getattr(fn, "args", None)
        if args is not None:
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                local.add(a.arg)
            if args.vararg:
                local.add(args.vararg.arg)
            if args.kwarg:
                local.add(args.kwarg.arg)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    _collect_target_names(t, local)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                _collect_target_names(node.target, local)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                _collect_target_names(node.target, local)
            elif isinstance(node, ast.comprehension):
                _collect_target_names(node.target, local)
            elif isinstance(node, ast.withitem) and node.optional_vars:
                _collect_target_names(node.optional_vars, local)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local.add(node.name)
        return local

    @staticmethod
    def _module_level_names(ctx: ModuleContext) -> Set[str]:
        """Imported module aliases (jnp, np, jax, …): `jnp.add(...)` is not a
        closed-over mutation however suspicious the method name."""
        names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    names.add(alias.asname or alias.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    names.add(alias.asname or alias.name)
        return names


def _collect_target_names(t: ast.AST, out: Set[str]) -> None:
    if isinstance(t, ast.Name):
        out.add(t.id)
    elif isinstance(t, (ast.Tuple, ast.List)):
        for e in t.elts:
            _collect_target_names(e, out)
    elif isinstance(t, ast.Starred):
        _collect_target_names(t.value, out)
    # Attribute/Subscript targets mutate existing objects — handled above
