"""proto-drift — the protoless pb2 regen's three-way contract.

This repo regenerates ``surge_tpu/log/log_service_pb2.py`` WITHOUT protoc
(tools/regen_log_proto.py patches the serialized FileDescriptorProto), keeps
``proto/log_service.proto`` in sync BY HAND, and routes message-reuse RPCs
through the hand-rolled ``METHODS`` table in ``surge_tpu/log/server.py``
rather than the descriptor. Three artifacts, zero compiler checks — PR 4's
regen shipped with the .proto comment block lagging the table until review
caught it. :func:`check_proto_drift` diffs all three pairwise:

- proto-file rpcs (declared + the ``//   Name(Req) returns (Reply)``
  message-reuse comment block) vs the METHODS route table;
- proto-file declared rpcs vs the pb2 descriptor's service;
- proto-file message fields (name = number) vs the pb2 descriptor's messages.

Inputs are injectable so the fixture corpus can exercise every drift class
without touching the real artifacts.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterator, List, Optional, Tuple

from surge_tpu.analysis.core import Finding, RepoContext, Rule, register

PROTO_PATH = "proto/log_service.proto"
SERVER_PATH = "surge_tpu/log/server.py"

_RPC_RE = re.compile(r"^\s*rpc\s+(\w+)\s*\(\s*(\w+)\s*\)\s*returns\s*\(\s*(\w+)\s*\)",
                     re.M)
_REUSE_RE = re.compile(r"^\s*//\s{1,4}(\w+)\((\w+)\)\s+returns\s+\((\w+)\)", re.M)
_MESSAGE_RE = re.compile(r"^\s*message\s+(\w+)\s*\{(.*?)\}", re.M | re.S)
_FIELD_RE = re.compile(
    r"^\s*(?:repeated\s+|optional\s+)?(?:map\s*<[^>]*>|[\w.]+)\s+(\w+)\s*=\s*(\d+)\s*;",
    re.M)

Sig = Tuple[str, str]  # (request message, reply message)


def parse_proto(text: str) -> Tuple[Dict[str, Sig], Dict[str, Sig],
                                    Dict[str, Dict[str, int]]]:
    """(declared rpcs, message-reuse comment rpcs, message fields)."""
    # reuse rpcs live IN comments; everything else parses comment-stripped
    # (a `}` inside a comment would otherwise truncate a message body)
    reuse = {m.group(1): (m.group(2), m.group(3))
             for m in _REUSE_RE.finditer(text)}
    stripped = re.sub(r"//[^\n]*", "", text)
    declared = {m.group(1): (m.group(2), m.group(3))
                for m in _RPC_RE.finditer(stripped)}
    messages: Dict[str, Dict[str, int]] = {}
    for m in _MESSAGE_RE.finditer(stripped):
        messages[m.group(1)] = {f.group(1): int(f.group(2))
                                for f in _FIELD_RE.finditer(m.group(2))}
    return declared, reuse, messages


def parse_methods_table(source: str) -> Dict[str, Sig]:
    """The METHODS route table from log/server.py, read via AST (no import
    side effects, works on fixture snippets too)."""
    tree = ast.parse(source)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "METHODS"
                and isinstance(node.value, ast.Dict)):
            continue
        table: Dict[str, Sig] = {}
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant) and isinstance(v, ast.Tuple)
                    and len(v.elts) == 2):
                continue
            req, reply = (e.attr if isinstance(e, ast.Attribute) else
                          e.id if isinstance(e, ast.Name) else "?"
                          for e in v.elts)
            table[k.value] = (req, reply)
        return table
    return {}


def descriptor_state() -> Tuple[Dict[str, Sig], Dict[str, Dict[str, int]]]:
    """(service methods, message fields) from the live pb2 descriptor."""
    from google.protobuf import descriptor_pb2
    from surge_tpu.log import log_service_pb2 as pb

    fd = descriptor_pb2.FileDescriptorProto()
    pb.DESCRIPTOR.CopyToProto(fd)
    services: Dict[str, Sig] = {}
    for svc in fd.service:
        for method in svc.method:
            services[method.name] = (method.input_type.split(".")[-1],
                                     method.output_type.split(".")[-1])
    messages = {m.name: {f.name: f.number for f in m.field}
                for m in fd.message_type}
    return services, messages


def check_proto_drift(
    proto_text: str,
    methods: Dict[str, Sig],
    pb2_services: Optional[Dict[str, Sig]] = None,
    pb2_messages: Optional[Dict[str, Dict[str, int]]] = None,
) -> List[str]:
    """Pairwise drift between the .proto contract, the METHODS route table
    and the pb2 descriptor. Returns human-readable drift lines (empty = in
    sync). pb2 sides are optional so text-only fixtures stay cheap."""
    declared, reuse, proto_messages = parse_proto(proto_text)
    all_proto = {**declared, **reuse}
    drift: List[str] = []

    for name in sorted(set(all_proto) - set(methods)):
        drift.append(f"rpc `{name}` is in proto/log_service.proto but has no "
                     "METHODS route in log/server.py")
    for name in sorted(set(methods) - set(all_proto)):
        drift.append(f"METHODS route `{name}` is not in proto/log_service.proto "
                     "(declare it, or document it in the message-reuse comment "
                     "block)")
    for name in sorted(set(methods) & set(all_proto)):
        if methods[name] != all_proto[name]:
            drift.append(
                f"rpc `{name}` signature drift: proto says "
                f"{all_proto[name][0]} -> {all_proto[name][1]}, METHODS routes "
                f"{methods[name][0]} -> {methods[name][1]}")

    if pb2_services is not None:
        for name in sorted(set(declared) - set(pb2_services)):
            drift.append(f"declared rpc `{name}` is missing from the pb2 "
                         "descriptor service — run tools/regen_log_proto.py")
        for name in sorted(set(pb2_services) - set(declared)):
            drift.append(f"pb2 descriptor rpc `{name}` is not declared in "
                         "proto/log_service.proto — sync the .proto by hand")
        for name in sorted(set(declared) & set(pb2_services)):
            if declared[name] != pb2_services[name]:
                drift.append(
                    f"rpc `{name}` signature drift: proto says "
                    f"{declared[name][0]} -> {declared[name][1]}, pb2 has "
                    f"{pb2_services[name][0]} -> {pb2_services[name][1]}")

    if pb2_messages is not None:
        for msg in sorted(set(proto_messages) - set(pb2_messages)):
            drift.append(f"message `{msg}` is in the .proto but not the pb2 "
                         "descriptor — run tools/regen_log_proto.py")
        for msg in sorted(set(proto_messages) & set(pb2_messages)):
            proto_fields, pb2_fields = proto_messages[msg], pb2_messages[msg]
            for fname in sorted(set(proto_fields) - set(pb2_fields)):
                drift.append(f"field `{msg}.{fname}` is in the .proto but not "
                             "the pb2 descriptor — run tools/regen_log_proto.py")
            for fname in sorted(set(pb2_fields) - set(proto_fields)):
                drift.append(f"field `{msg}.{fname}` is in the pb2 descriptor "
                             "but not the .proto — the protoless regen added "
                             "it; sync proto/log_service.proto by hand")
            for fname in sorted(set(proto_fields) & set(pb2_fields)):
                if proto_fields[fname] != pb2_fields[fname]:
                    drift.append(
                        f"field `{msg}.{fname}` number drift: .proto says "
                        f"{proto_fields[fname]}, pb2 has {pb2_fields[fname]}")
        sigs = {n for sig in {**methods, **all_proto}.values() for n in sig}
        for missing in sorted(sigs - set(pb2_messages) - {"?"}):
            drift.append(f"message `{missing}` referenced by an rpc signature "
                         "does not exist in the pb2 descriptor")
    return drift


def repo_drift(repo_root: str) -> List[str]:
    """The real repo's three-way check (what --check and the lint rule run)."""
    with open(os.path.join(repo_root, PROTO_PATH), encoding="utf-8") as f:
        proto_text = f.read()
    with open(os.path.join(repo_root, SERVER_PATH), encoding="utf-8") as f:
        methods = parse_methods_table(f.read())
    if not methods:
        return [f"no METHODS table found in {SERVER_PATH}"]
    services, messages = descriptor_state()
    return check_proto_drift(proto_text, methods, services, messages)


@register
class ProtoDrift(Rule):
    id = "proto-drift"
    summary = "proto file / METHODS route table / pb2 descriptor out of sync"
    repo_scope = True

    def check_repo(self, ctx: RepoContext) -> Iterator[Finding]:
        try:
            lines = repo_drift(ctx.repo_root)
        except Exception as exc:
            yield Finding(rule=self.id, path=PROTO_PATH, line=1,
                          message=f"proto drift check failed: {exc}")
            return
        for msg in lines:
            yield Finding(rule=self.id, path=PROTO_PATH, line=1, message=msg)
