"""Registry-sync rules — config keys and metric instruments vs their
registries, docs rows and golden catalogs.

These are the static twins of the runtime coupling tests
(tests/test_exposition.py's catalog-completeness parametrization and the
golden `.om` comparisons): the runtime tests prove REGISTERED instruments are
cataloged, but only see registries a test happens to construct; these rules
read every creation site in the source, so an instrument or config key added
in a module no test renders still cannot drift.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from surge_tpu.analysis.core import Finding, ModuleContext, RepoContext, Rule, register

CONFIG_MODULE = "surge_tpu/config/__init__.py"
OPERATIONS_DOC = "docs/operations.md"
OBSERVABILITY_DOC = "docs/observability.md"
GOLDEN_PATHS = ("tests/golden/metrics.om", "tests/golden/metrics_broker.om",
                "tests/golden/metrics_fleet.om")
#: instrument-creation modules the golden files render end to end — names
#: created here must ALSO appear in a golden (regen + docs move together)
GOLDEN_COUPLED_MODULES = ("surge_tpu/metrics/__init__.py",
                          "surge_tpu/metrics/broker.py",
                          "surge_tpu/metrics/fleet.py")
#: SLO definitions reference merged-payload FAMILY names — every family an
#: ``SLO(...)`` cites must be rendered by some golden exposition, or the
#: objective watches a metric nothing emits (a dead objective never pages)
SLO_MODULE = "surge_tpu/observability/slo.py"

_ACCESSORS = frozenset({"get", "get_int", "get_float", "get_bool", "get_str",
                        "get_seconds", "get_int_list"})

#: `surge.log.compaction.{enabled, interval-ms}` rows and `surge.producer.*`
#: wildcard mentions both count as documentation
_BRACE_RE = re.compile(r"(surge\.[\w.-]*?)\{([^}]*)\}")
_PLAIN_RE = re.compile(r"surge\.[\w-]+(?:\.[\w*-]+)*")


def documented_keys(text: str) -> Tuple[Set[str], Set[str]]:
    """(exact keys, wildcard prefixes) mentioned in a markdown doc."""
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    for m in _BRACE_RE.finditer(text):
        prefix = m.group(1)
        for item in m.group(2).split(","):
            # rows annotate keys in place — `{linger-ms (2 — the linger
            # trigger), batch-max-records (512)}` — so each item's KEY is its
            # first token; annotation fragments produced by commas inside a
            # parenthetical yield garbage tokens that match no read key
            token = item.strip().strip("`").split()
            if token:
                exact.add(prefix + token[0].strip("`"))
    for m in _PLAIN_RE.finditer(text):
        key = m.group(0)
        if key.endswith(".*"):
            prefixes.add(key[:-1])  # keep the trailing dot
        elif "{" not in key:
            exact.add(key.rstrip("."))
    return exact, prefixes


def _is_documented(key: str, exact: Set[str], prefixes: Set[str]) -> bool:
    return key in exact or any(key.startswith(p) for p in prefixes)


def config_reads(ctx: ModuleContext) -> List[Tuple[str, int]]:
    """(key, line) for every typed-accessor read of a literal surge.* key."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and node.func.attr in _ACCESSORS and node.args):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value.startswith("surge."):
            out.append((arg.value, node.lineno))
    return out


def _string_constants(ctx: ModuleContext) -> Set[str]:
    return {n.value for n in ast.walk(ctx.tree)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)
            and n.value.startswith("surge.")}


@register
class ConfigKeyRegistry(Rule):
    """Every ``surge.*`` config key read in code must exist in the
    ``surge_tpu/config`` DEFAULTS registry AND have a row in
    docs/operations.md; a DEFAULTS key nothing reads is dead weight.

    History: by PR 7 a dozen keys (``surge.log.quorum.*``,
    ``surge.store.checkpoint.*``, ``surge.metrics.exemplars``, …) were read
    straight through ``Config.get`` fallbacks without a DEFAULTS row — their
    env-override spelling was invisible, ``with_overrides`` keyword
    canonicalization silently missed them, and the operations doc lagged the
    code. The registry IS the contract; this rule machine-checks it.
    """

    id = "config-key-registry"
    summary = "surge.* key read without a DEFAULTS row / docs row, or never read"
    repo_scope = True

    def check_repo(self, ctx: RepoContext) -> Iterator[Finding]:
        try:
            from surge_tpu.config import DEFAULTS
        except Exception as exc:  # pragma: no cover — config import is jax-free
            yield Finding(rule=self.id, path=CONFIG_MODULE, line=1,
                          message=f"cannot import config DEFAULTS: {exc}")
            return
        cfg_ctx = ctx.module(CONFIG_MODULE)
        doc_exact, doc_prefixes = documented_keys(ctx.doc_text(OPERATIONS_DOC))

        reads: Dict[str, Tuple[ModuleContext, int]] = {}
        mentioned: Set[str] = set()
        for mod in ctx.modules:
            # typed accessor bundles (TimeoutConfig/RetryConfig) read keys
            # from inside the config module itself — those reads count; its
            # string CONSTANTS don't (the DEFAULTS dict would mark every key
            # "mentioned" and blind the dead-row check)
            for key, line in config_reads(mod):
                reads.setdefault(key, (mod, line))
            if mod.rel_path != CONFIG_MODULE:
                mentioned |= _string_constants(mod)

        for key in sorted(reads):
            mod, line = reads[key]
            if key not in DEFAULTS:
                yield Finding(
                    rule=self.id, path=mod.rel_path, line=line,
                    message=(f"config key `{key}` is read here but has no "
                             "DEFAULTS row in surge_tpu/config — its env "
                             "override spelling and with_overrides keyword "
                             "canonicalization are invisible; register it"),
                    snippet=mod.line_text(line))
                # a docs row for an unregistered key is reported once the
                # DEFAULTS row exists; one drift, one finding
                continue
            if not _is_documented(key, doc_exact, doc_prefixes):
                yield Finding(
                    rule=self.id, path=mod.rel_path, line=line,
                    message=(f"config key `{key}` has no row in "
                             f"{OPERATIONS_DOC} — add it to the config table"),
                    snippet=mod.line_text(line))

        for key in sorted(DEFAULTS):
            line = self._defaults_line(cfg_ctx, key)
            if key not in reads and key not in mentioned:
                yield Finding(
                    rule=self.id, path=CONFIG_MODULE, line=line,
                    message=(f"DEFAULTS key `{key}` is never read in "
                             "surge_tpu/tools/bench.py — dead registry row "
                             "(remove it or wire the feature that reads it)"),
                    snippet=cfg_ctx.line_text(line) if cfg_ctx else "")
            if key not in reads and not _is_documented(key, doc_exact,
                                                       doc_prefixes):
                # read keys already reported their missing docs row above
                yield Finding(
                    rule=self.id, path=CONFIG_MODULE, line=line,
                    message=(f"DEFAULTS key `{key}` has no row in "
                             f"{OPERATIONS_DOC} — add it to the config table"),
                    snippet=cfg_ctx.line_text(line) if cfg_ctx else "")

    @staticmethod
    def _defaults_line(cfg_ctx: Optional[ModuleContext], key: str) -> int:
        if cfg_ctx is None:
            return 1
        needle = f'"{key}"'
        for i, text in enumerate(cfg_ctx.lines, start=1):
            if needle in text:
                return i
        return 1


@register
class MetricCatalog(Rule):
    """Instrument names created in code must appear in the
    docs/observability.md catalog; names created in the engine/broker quiver
    modules must ALSO be in the golden ``.om`` files.

    History: the golden/catalog coupling (PR 1, extended to the broker in
    PR 5) is enforced at runtime only for registries the exposition tests
    construct — the multilanguage gateway's timers drifted out of the docs
    catalog unnoticed because no test renders that registry. This rule reads
    every ``MetricInfo("surge.…")`` creation site instead.
    """

    id = "metric-catalog"
    summary = "MetricInfo name missing from docs catalog / golden .om files"
    repo_scope = True

    def check_repo(self, ctx: RepoContext) -> Iterator[Finding]:
        try:
            from surge_tpu.metrics.exposition import sanitize_name
        except Exception:  # pragma: no cover
            def sanitize_name(n: str) -> str:
                return re.sub(r"[^a-zA-Z0-9_:]", "_", n)
        docs = ctx.doc_text(OBSERVABILITY_DOC)
        golden_families: Set[str] = set()
        for rel in GOLDEN_PATHS:
            for m in re.finditer(r"^# TYPE (\S+) ", ctx.doc_text(rel), re.M):
                golden_families.add(m.group(1))

        for mod in ctx.modules:
            if mod.rel_path == SLO_MODULE:
                for name, line in self._slo_names(mod):
                    # the objective NAME is the operator vocabulary — burn
                    # pages, surgetop's breach column and the runbooks all
                    # speak it; an undocumented objective pages in a word
                    # docs/observability.md cannot explain
                    if name not in docs:
                        yield Finding(
                            rule=self.id, path=mod.rel_path, line=line,
                            message=(f"SLO objective `{name}` is missing "
                                     f"from the {OBSERVABILITY_DOC} SLO "
                                     "table — document its target and what "
                                     "a burn page means"),
                            snippet=mod.line_text(line))
                for fam, line in self._slo_families(mod):
                    if not any(g == fam or g.startswith(fam + "_")
                               for g in golden_families):
                        yield Finding(
                            rule=self.id, path=mod.rel_path, line=line,
                            message=(f"SLO references family `{fam}` which "
                                     "no golden exposition renders — a dead "
                                     "objective (fix the family name, or "
                                     "catalog+regen the instrument it "
                                     "watches)"),
                            snippet=mod.line_text(line))
            for name, line in self._instrument_names(mod):
                if name not in docs:
                    yield Finding(
                        rule=self.id, path=mod.rel_path, line=line,
                        message=(f"instrument `{name}` is missing from the "
                                 f"{OBSERVABILITY_DOC} metric catalog"),
                        snippet=mod.line_text(line))
                if mod.rel_path in GOLDEN_COUPLED_MODULES:
                    fam = sanitize_name(name)
                    if not any(g == fam or g.startswith(fam + "_")
                               for g in golden_families):
                        yield Finding(
                            rule=self.id, path=mod.rel_path, line=line,
                            message=(f"instrument `{name}` is missing from the "
                                     "golden .om files — run tools/"
                                     "regen_golden_metrics.py (golden and docs "
                                     "catalog move together)"),
                            snippet=mod.line_text(line))

    @staticmethod
    def _slo_names(mod: ModuleContext) -> Iterator[Tuple[str, int]]:
        """(objective name, line) for every ``SLO("name", ...)`` literal
        in the SLO module (positional ``name`` is arg index 0)."""
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = node.func.id if isinstance(node.func, ast.Name) else (
                node.func.attr if isinstance(node.func, ast.Attribute)
                else None)
            if leaf != "SLO":
                continue
            literals = list(node.args[:1])
            literals.extend(kw.value for kw in node.keywords
                            if kw.arg == "name")
            for arg in literals:
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                str) \
                        and arg.value:
                    yield arg.value, node.lineno

    @staticmethod
    def _slo_families(mod: ModuleContext) -> Iterator[Tuple[str, int]]:
        """(family, line) for every ``SLO(... family=/good_family=...)``
        literal in the SLO module (positional ``family`` is arg index 1)."""
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = node.func.id if isinstance(node.func, ast.Name) else (
                node.func.attr if isinstance(node.func, ast.Attribute)
                else None)
            if leaf != "SLO":
                continue
            literals = []
            if len(node.args) > 1:
                literals.append(node.args[1])
            literals.extend(kw.value for kw in node.keywords
                            if kw.arg in ("family", "good_family"))
            for arg in literals:
                if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                                str) \
                        and arg.value:
                    yield arg.value, node.lineno

    @staticmethod
    def _instrument_names(mod: ModuleContext) -> Iterator[Tuple[str, int]]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = node.func.id if isinstance(node.func, ast.Name) else (
                node.func.attr if isinstance(node.func, ast.Attribute) else None)
            if leaf not in ("MetricInfo", "MI") or not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                    and arg.value.startswith("surge."):
                yield arg.value, node.lineno
