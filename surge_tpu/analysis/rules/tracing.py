"""Tracing rules — span lifecycle defects (ISSUE 14).

With tail-based sampling the cost of a leaked span grew: an unfinished span
never reaches the exporter OR the tail sampler, so its trace never quiesces —
the trace buffers until the span-buffer bound evicts it, and a keep-worthy
incident trace silently vanishes from the ring. Before the tail plane a leak
just lost one span; now it loses the whole trace's anatomy.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Tuple

from surge_tpu.analysis.core import Finding, ModuleContext, Rule, register


def _is_start_span(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "start_span")


def _scope_items(fn: ast.AST) -> Iterator[Tuple[ast.AST, ast.AST]]:
    """(node, parent) pairs within one function scope (nested function /
    lambda / class bodies excluded — they execute elsewhere and are analyzed
    as their own scopes)."""
    stack: List[Tuple[ast.AST, ast.AST]] = [(c, fn)
                                            for c in ast.iter_child_nodes(fn)]
    while stack:
        node, parent = stack.pop()
        yield node, parent
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend((c, node) for c in ast.iter_child_nodes(node))


def _finish_on(node: ast.AST, name: str) -> bool:
    """Whether ``<name>.finish()`` appears anywhere under ``node``."""
    for n in ast.walk(node):
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr == "finish"
                and isinstance(n.func.value, ast.Name)
                and n.func.value.id == name):
            return True
    return False


@register
class SpanLeak(Rule):
    """A ``start_span(...)`` whose result is neither used as a context
    manager nor ``.finish()``ed on every path (except paths included).

    History: the replay profiler's ``record()`` finished its stage span only
    on the straight-line path (fixed alongside this rule), and the ISSUE-14
    tail sampler turned that defect class from "one span lost" into "the
    whole trace's anatomy lost" (module doc). The safe shapes are ``with
    tracer.start_span(...)``, ``with span:`` after attribute setup, or
    ``span.finish()`` inside a ``finally``; a span that ESCAPES the function
    (returned, stored on an attribute, passed as an argument) is someone
    else's lifecycle and is not flagged here.
    """

    id = "span-leak"
    summary = ("start_span result neither context-managed nor finish()ed "
               "on every path")

    def check_module(self, ctx: ModuleContext) -> Iterator[Finding]:
        if "start_span" not in ctx.source:
            return
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(ctx, fn)

    def _check_function(self, ctx: ModuleContext,
                        fn: ast.AST) -> Iterator[Finding]:
        items = list(_scope_items(fn))
        assigned: Dict[str, ast.Call] = {}
        for node, parent in items:
            if not _is_start_span(node):
                continue
            if isinstance(parent, ast.withitem):
                continue  # `with tracer.start_span(...):` — managed
            if isinstance(parent, ast.Expr):
                yield self.finding(
                    ctx, node,
                    "start_span(...) result discarded — the span can never "
                    "finish; use `with tracer.start_span(...)` or keep the "
                    "handle and finish() it in a finally")
                continue
            if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                    and isinstance(parent.targets[0], ast.Name):
                assigned[parent.targets[0].id] = node
                continue
            # every other shape (returned, attribute/subscript store, call
            # argument, tuple element) escapes this scope: lifecycle owned
            # elsewhere, not analyzable here
        for name, call in assigned.items():
            yield from self._check_name(ctx, fn, items, name, call)

    def _check_name(self, ctx: ModuleContext, fn: ast.AST, items,
                    name: str, call: ast.Call) -> Iterator[Finding]:
        finish_anywhere = False
        for node, parent in items:
            # `with span:` anywhere in the function — managed
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Name) and expr.id == name:
                        return
            # finish() inside a finally — covered on every path
            if isinstance(node, ast.Try) and any(
                    _finish_on(stmt, name) for stmt in node.finalbody):
                return
            if isinstance(node, ast.Name) and node.id == name:
                if self._escapes(node, parent):
                    return
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "finish"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == name):
                finish_anywhere = True
        if finish_anywhere:
            yield self.finding(
                ctx, call,
                f"span `{name}` is finish()ed only on some paths — an "
                "exception between start_span and finish() leaks it (and "
                "its whole trace under tail sampling); move the finish() "
                "into a finally or use `with`")
        else:
            yield self.finding(
                ctx, call,
                f"span `{name}` is never finish()ed in this function and "
                "never escapes it — the span (and its whole trace under "
                "tail sampling) is leaked")

    @staticmethod
    def _escapes(node: ast.Name, parent: ast.AST) -> bool:
        """The span handle leaves this scope: returned/yielded, stored on an
        attribute or subscript, passed as a call argument, or packed into a
        tuple (conservatively treated as escaping)."""
        if isinstance(parent, (ast.Return, ast.Yield, ast.YieldFrom,
                               ast.Tuple, ast.List, ast.keyword)):
            return True
        if isinstance(parent, ast.Call) and node in parent.args:
            return True
        if isinstance(parent, ast.Assign) and node is parent.value and any(
                isinstance(t, (ast.Attribute, ast.Subscript))
                for t in parent.targets):
            return True
        return False

    # ``finding`` helper inherited from Rule uses node.lineno — ast.Call
    # linenos anchor at the call, which is the span's creation site.
