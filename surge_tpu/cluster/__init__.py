"""Cluster self-healing plane: partition routing, the autobalancer, chaos soak.

The composition layer ROADMAP item 1 asked for: the broker already has
per-partition vote/fence/hwm machinery (PR 7), a deterministic fault plane
(PR 4), federated scrape + SLO burn rates (PR 9) and a fenced
``HandoffPartition`` — this package closes the loop so the fleet survives
broker churn and load skew without an operator:

- :class:`~surge_tpu.cluster.router.PartitionRouter` — a LogTransport-
  protocol client that learns the cluster's partition→leader map
  (``ClusterMeta`` bootstrap fetch) and routes every producer commit and
  read to the partition's CURRENT leader, invalidating its cache on
  ``NOT_LEADER``/fence/connect failure;
- :class:`~surge_tpu.cluster.autobalancer.Autobalancer` — a supervised
  ``Controllable`` that consumes one federated-scrape pass + the SLO
  engine's burn rates per cycle, scores brokers on burn/lag/lead-count, and
  drives planned per-partition ``HandoffPartition`` moves off burning or
  overloaded brokers (hysteresis, a move budget per window, dry-run mode;
  every decision lands on its flight recorder);
- :mod:`~surge_tpu.cluster.soak` — the seeded chaos soak that proves the
  whole loop: rolling kills, fsync stalls, link faults, membership churn
  and Zipf hot-key skew on a 3+-broker fleet, scored by the SLO engine with
  a 0-lost / 0-duplicated / exactly-one-leader-per-partition verdict
  (``SURGE_BENCH_SOAK=1``; the 3-seed fast variant runs in tier-1).
"""

from surge_tpu.cluster.autobalancer import Autobalancer
from surge_tpu.cluster.router import PartitionRouter, RoutedProducer

__all__ = ["Autobalancer", "PartitionRouter", "RoutedProducer"]
