"""SLO-driven autobalancer: the control loop that keeps the spread healthy.

One cycle = one federated-scrape pass (which also evaluates the SLO engine)
plus one ClusterMeta fetch, scored into per-broker rows:

- ``up`` — did the member answer the scrape (down members are failover
  candidates the COORDINATOR's reassign-grace sweep owns; the balancer only
  records the observation);
- ``leads`` — partition indices led (from the assignment map);
- ``lag`` — the member's ``surge_log_hwm_lag_records`` gauge (how far its
  applied frontier runs ahead of the quorum-acked one: the load signal);
- ``burning`` — whether any SLO objective is in breach this cycle.

Decisions: when the lead-count skew across UP members exceeds
``surge.cluster.balancer.max-lead-skew`` — or an SLO is burning and one up
member carries a clearly-worst lag — the balancer drives ONE planned
per-partition ``HandoffPartition`` move per cycle from the busiest member to
the least loaded, under three brakes: per-partition **hysteresis** (a
just-moved partition is not moved again within the window), a **move
budget** per time window, and **dry-run** mode (decide + record, never
move). Every decision — executed, skipped, or dry — lands on the balancer's
flight recorder, so a heal is reconstructable from the merged timeline next
to the broker-side promotion/fence/reassign events it caused.

Supervision: the balancer is a :class:`~surge_tpu.common.Controllable`
(async start/stop around a daemon thread), registrable with the health
supervisor like any other component; ``cycle()`` is also directly callable
for deterministic tests and the chaos soak.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from surge_tpu.common import Ack, Controllable, logger
from surge_tpu.config import Config, default_config
from surge_tpu.observability.flight import FlightRecorder

__all__ = ["Autobalancer"]


class Autobalancer(Controllable):
    """Scrape → score → (maybe) move one partition. See the module doc."""

    def __init__(self, scraper, brokers, config: Config | None = None,
                 slo=None, metrics=None, flight: FlightRecorder | None = None,
                 transport_factory: Optional[Callable[[str], object]] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        cfg = config or default_config()
        self.scraper = scraper
        #: bootstrap broker addresses for the ClusterMeta fetch (any member)
        self.brokers = ([b.strip() for b in brokers.split(",") if b.strip()]
                        if isinstance(brokers, str) else list(brokers))
        self.slo = slo if slo is not None else getattr(scraper, "slo", None)
        self.metrics = metrics if metrics is not None \
            else getattr(scraper, "metrics", None)
        self.flight = flight if flight is not None else FlightRecorder(
            name="autobalancer", role="balancer")
        self._clock = clock
        self.interval_s = cfg.get_seconds(
            "surge.cluster.balancer.interval-ms", 5_000)
        self.move_budget = cfg.get_int("surge.cluster.balancer.move-budget",
                                       4)
        self.window_s = cfg.get_seconds("surge.cluster.balancer.window-ms",
                                        60_000)
        self.hysteresis_s = cfg.get_seconds(
            "surge.cluster.balancer.hysteresis-ms", 30_000)
        self.max_lead_skew = max(1, cfg.get_int(
            "surge.cluster.balancer.max-lead-skew", 1))
        self.dry_run = cfg.get_bool("surge.cluster.balancer.dry-run", False)
        self._config = cfg
        self._transport_factory = transport_factory
        self._transports: Dict[str, object] = {}
        #: partition key -> monotonic stamp of OUR last move of it
        self._last_move: Dict[str, float] = {}
        #: monotonic stamps of executed moves inside the budget window
        self._moves: List[float] = []
        self.cycles = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle (supervised Controllable) ----------------------------------------------

    async def start(self) -> Ack:
        self.start_sync()
        return Ack()

    async def stop(self) -> Ack:
        self.stop_sync()
        return Ack()

    def start_sync(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._run,
                                            name="surge-autobalancer",
                                            daemon=True)
            self._thread.start()

    def stop_sync(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(self.interval_s + 2.0)
        self._thread = None
        for t in self._transports.values():
            try:
                t.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        self._transports.clear()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.cycle()
            except Exception:  # noqa: BLE001 — the loop must survive a bad pass
                logger.exception("autobalancer cycle failed; continuing")

    # -- transports -----------------------------------------------------------------------

    def _transport(self, addr: str):
        hit = self._transports.get(addr)
        if hit is None:
            if self._transport_factory is not None:
                hit = self._transport_factory(addr)
            else:
                from surge_tpu.log.client import GrpcLogTransport

                hit = GrpcLogTransport(addr, config=self._config)
            self._transports[addr] = hit
        return hit

    def _drop_transport(self, addr: str) -> None:
        t = self._transports.pop(addr, None)
        if t is not None:
            try:
                t.close()
            except Exception:  # noqa: BLE001
                pass

    def _fetch_meta(self) -> Optional[dict]:
        for addr in list(self.brokers):
            try:
                return self._transport(addr).cluster_meta()
            except Exception:  # noqa: BLE001 — try the next bootstrap broker
                self._drop_transport(addr)
        return None

    # -- one decision pass ----------------------------------------------------------------

    def cycle(self) -> dict:
        """One scrape→score→decide pass; returns the decision record (also
        flight-recorded). Safe to call directly (tests, the soak's
        deterministic loop) — the background thread just calls it on a
        timer."""
        self.cycles += 1
        summary = self.scraper.scrape_once()
        meta = self._fetch_meta()
        if meta is None:
            out = {"decision": "skip", "reason": "no-member-reachable",
                   "errors": summary.get("errors")}
            self.flight.record("balance.skip", **out)
            return out
        merged = self.scraper.last_merged()  # one merge, both extractions
        up = self.scraper.instance_values("up", merged=merged)
        lag = self.scraper.instance_values("surge_log_hwm_lag_records",
                                           merged=merged)
        assignments: Dict[str, str] = dict(meta.get("assignments") or {})
        members: List[str] = list(meta.get("members") or [])
        burning = list(self.slo.breached()) if self.slo is not None else []
        rows: Dict[str, dict] = {}
        for m in members:
            leads = sorted(int(k) for k, v in assignments.items() if v == m)
            rows[m] = {"up": bool(up.get(m, 0.0)),
                       "leads": leads,
                       "lag": float(lag.get(m, 0.0))}
        if self.metrics is not None:
            counts = [len(r["leads"]) for r in rows.values() if r["up"]]
            skew = (max(counts) - min(counts)) if counts else 0
            self.metrics.balancer_cycles.record()
            self.metrics.balancer_lead_skew.record(skew)
        decision = self._decide(rows, burning)
        decision["cycle"] = self.cycles
        if burning:
            decision["burning"] = burning
        self.flight.record("balance." + ("move" if decision["decision"]
                                         == "move" else "skip"),
                           **{k: v for k, v in decision.items()
                              if k != "decision"})
        if decision["decision"] == "move" and not decision.get("dry_run"):
            self._execute(decision)
        elif (decision["decision"] == "move"  # dry-run
              or decision.get("reason") in ("hysteresis", "move-budget")):
            # every decided-but-not-executed move counts here — dry-run,
            # hysteresis and budget throttling are all operator-visible
            if self.metrics is not None:
                self.metrics.balancer_skipped.record()
        return decision

    def _decide(self, rows: Dict[str, dict], burning: List[str]) -> dict:
        """Pick (source, destination, partition) or a skip reason. Pure
        given its inputs — the brakes (hysteresis/budget) read balancer
        state but mutate nothing until the move executes."""
        now = self._clock()
        up_rows = {m: r for m, r in rows.items() if r["up"]}
        if len(up_rows) < 2:
            return {"decision": "skip", "reason": "fewer-than-2-up-members",
                    "rows": rows}
        busiest = max(up_rows, key=lambda m: (len(up_rows[m]["leads"]),
                                              up_rows[m]["lag"]))
        calmest = min(up_rows, key=lambda m: (len(up_rows[m]["leads"]),
                                              up_rows[m]["lag"]))
        skew = len(up_rows[busiest]["leads"]) - len(up_rows[calmest]["leads"])
        hot = None
        if burning:
            # SLO burning: attribute to the up member with the clearly-worst
            # hwm lag (its applied frontier is running away from the quorum)
            by_lag = sorted(up_rows, key=lambda m: up_rows[m]["lag"],
                            reverse=True)
            if (up_rows[by_lag[0]]["lag"] > 0
                    and up_rows[by_lag[0]]["leads"]
                    and (len(by_lag) < 2 or up_rows[by_lag[0]]["lag"]
                         >= 2.0 * up_rows[by_lag[1]]["lag"])):
                hot = by_lag[0]
        if hot is None and skew <= self.max_lead_skew:
            return {"decision": "skip", "reason": "within-skew",
                    "skew": skew, "rows": rows}
        source = hot or busiest
        dest = calmest if calmest != source else min(
            (m for m in up_rows if m != source),
            key=lambda m: len(up_rows[m]["leads"]))
        movable = [p for p in up_rows[source]["leads"]
                   if now - self._last_move.get(str(p), -1e9)
                   >= self.hysteresis_s]
        if not movable:
            return {"decision": "skip", "reason": "hysteresis",
                    "source": source, "skew": skew}
        self._moves = [t for t in self._moves if now - t < self.window_s]
        if len(self._moves) >= self.move_budget:
            return {"decision": "skip", "reason": "move-budget",
                    "budget": self.move_budget, "window_s": self.window_s}
        return {"decision": "move", "partition": movable[0],
                "source": source, "dest": dest, "skew": skew,
                "reason": "slo-burn" if hot else "lead-skew",
                "dry_run": self.dry_run}

    def _execute(self, decision: dict) -> None:
        source, dest = decision["source"], decision["dest"]
        partition = decision["partition"]
        try:
            t = self._transport(source)
            stats = t.cluster_handoff(dest, partition)
        except Exception as exc:  # noqa: BLE001 — the next cycle re-decides
            self._drop_transport(source)
            if self.metrics is not None:
                self.metrics.balancer_skipped.record()
            self.flight.record("balance.move-failed", partition=partition,
                              source=source, dest=dest, error=repr(exc)[:200])
            logger.warning("balancer move of partition %s %s->%s failed: %r",
                           partition, source, dest, exc)
            return
        now = self._clock()
        self._last_move[str(partition)] = now
        self._moves.append(now)
        if self.metrics is not None:
            self.metrics.balancer_moves.record()
        self.flight.record("balance.moved", partition=partition,
                           source=source, dest=dest,
                           fence_ms=stats.get("fence_ms"),
                           tail_records=stats.get("tail_records"))
        logger.warning("balancer moved partition %s %s -> %s (%s)",
                       partition, source, dest, decision["reason"])
