"""PartitionRouter — partition→leader routing under the LogTransport protocol.

With leadership spread (``ClusterMeta``), N brokers each lead a slice of the
partition indices. A plain :class:`~surge_tpu.log.client.GrpcLogTransport`
talks to ONE broker and treats every ``NOT_LEADER`` as a whole-broker
failover; the router instead learns the cluster's partition→leader map once
(bootstrap fetch from any member) and pins each operation to its partition's
CURRENT leader:

- one cached child transport per broker address (lazy);
- a producer (:class:`RoutedProducer`) buffers like any transactional
  producer and, at commit, ships the batch to the batch's partition leader —
  re-resolving through a fresh metadata fetch when the broker answers
  ``NOT_LEADER``/fenced or drops the connection, so a mid-commit handoff or
  failover costs one retry, not a publisher re-init storm;
- the leader cache is invalidated **per partition** on every redirect
  (``invalidate_partition`` — the publisher's fenced→re-init ladder calls it
  before re-opening), never kept stale forever;
- exactly-once across moves rests on the broker plane: the txn-dedup table
  replicates with the partition, so a verbatim retry on the NEW leader is
  answered from cache (or absorbed by the reopen alias window), never
  appended twice.

The router implements the LogTransport surface the engine/publisher uses
(``create_topic``/``topic``/``transactional_producer``/``read``/
``end_offset``/``latest_by_key``/``wait_for_append``), so it drops in as the
engine's ``log=`` — the publisher learns the partition→leader map without a
line of engine code changing.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence

import grpc

from surge_tpu.common import logger
from surge_tpu.log.client import GrpcLogTransport
from surge_tpu.log.transport import (
    LogRecord,
    NotLeaderError,
    ProducerFencedError,
    TopicSpec,
    TransactionStateError,
)

__all__ = ["PartitionRouter", "RoutedProducer"]

#: exceptions that mean "this broker is not (or no longer) the partition's
#: leader — re-resolve and retry", as opposed to logic errors that propagate
_REROUTE_ERRORS = (ProducerFencedError, NotLeaderError, grpc.RpcError)


def _router_span(tracer, name: str, **attrs):
    """A routing-hop span (ISSUE 14 satellite: resolve/redirect/retry hops
    were invisible) — opened ONLY under an already-active span, so the
    command path gets its router legs while unparented pollers (a tailing
    indexer's reads) cannot root a trace storm. The child transport's
    broker-call spans parent on this one via ``active_span()``, and their
    traceparent metadata carries the SAME trace to the broker — an A→B→A
    redirect stays one contiguous trace."""
    if tracer is None:
        return None
    from surge_tpu.tracing import active_span

    parent = active_span()
    if parent is None:
        return None
    span = tracer.start_span(name, parent=parent)
    for k, v in attrs.items():
        span.set_attribute(k, v)
    return span


class _RoutedHandle:
    """PipelinedCommit facade over whichever inner broker handle currently
    carries the dispatch. ``producer`` is the RoutedProducer itself — the
    publisher's retry gate identity-checks it — and ``future``/``seq``/
    ``records`` proxy the CURRENT inner handle, so a reroute that re-
    dispatched on a new leader is transparent to the awaiting commit task
    (it re-reads ``.future`` after ``retry_pipelined``)."""

    __slots__ = ("producer", "partition", "addr", "inner")

    def __init__(self, producer: "RoutedProducer", partition: int,
                 addr: str, inner) -> None:
        self.producer = producer
        self.partition = partition
        self.addr = addr
        self.inner = inner

    @property
    def future(self):
        return self.inner.future

    @property
    def seq(self) -> int:
        return self.inner.seq

    @property
    def records(self):
        return self.inner.records


class RoutedProducer:
    """Transactional producer over the router: one inner producer per broker
    the partition map has sent us to, opened lazily and re-opened after a
    fence. A batch commits on its partition's current leader; the retry
    ladder re-resolves the leader between attempts.

    ``commit_pipelined`` keeps PR-3's bounded in-flight window across
    partition moves (ROADMAP 4(b)): dispatches ship without awaiting
    earlier replies exactly like the direct gRPC client, and a failed
    handle's ``retry_pipelined`` re-resolves the leader — same broker →
    verbatim same-seq resend answered from the broker's dedup window;
    moved leader → the same records re-dispatched fresh on the new leader,
    where the replicated txn-dedup state absorbs a landed-but-unacked
    commit (the sync reroute ladder's proven exactly-once semantics)."""

    def __init__(self, router: "PartitionRouter", transactional_id: str,
                 attempts: int = 6) -> None:
        self._router = router
        self.transactional_id = transactional_id
        self._attempts = attempts
        self._buffer: Optional[List[LogRecord]] = None
        self._inner: Dict[str, object] = {}  # addr -> GrpcTxnProducer
        self._fenced = False

    @property
    def fenced(self) -> bool:
        return self._fenced

    @property
    def in_transaction(self) -> bool:
        return self._buffer is not None

    def begin(self) -> None:
        if self._buffer is not None:
            raise TransactionStateError("transaction already open")
        self._buffer = []

    def send(self, record: LogRecord) -> None:
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        self._buffer.append(record)

    def abort(self) -> None:
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        self._buffer = None  # records never left this process

    def commit(self) -> Sequence[LogRecord]:
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        records, self._buffer = self._buffer, None
        return self._routed(records, "commit")

    def commit_unsequenced(self) -> Sequence[LogRecord]:
        """Seq-less commit (epoch markers): same routing, no idempotency
        number — duplicates are harmless by the caller's contract."""
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        records, self._buffer = self._buffer, None
        return self._routed(records, "commit_unsequenced")

    def send_immediate(self, record: LogRecord) -> LogRecord:
        return self._routed([record], "send_immediate")

    def _inner_for(self, addr: str):
        inner = self._inner.get(addr)
        if inner is None or inner.fenced:
            inner = self._router._child(addr).transactional_producer(
                self.transactional_id)
            self._inner[addr] = inner
        return inner

    def commit_pipelined(self) -> _RoutedHandle:
        """Dispatch the buffered transaction on the partition's current
        leader WITHOUT awaiting the reply (the bounded-window write path)."""
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        records, self._buffer = self._buffer, None
        partition = self._partition_of(records)
        addr = self._router.leader_for(partition)
        inner = self._inner_for(addr)
        if getattr(inner, "in_transaction", False):
            inner.abort()  # local buffer left by an earlier dispatch failure
        inner.begin()
        for r in records:
            inner.send(r)
        return _RoutedHandle(self, partition, addr, inner.commit_pipelined())

    def retry_pipelined(self, handle: _RoutedHandle) -> _RoutedHandle:
        """Retry a failed pipelined commit wherever the partition now lives.

        Same leader + same inner producer → the inner client's verbatim
        same-seq resend (broker dedup answers a landed commit from cache).
        A reroute-class failure (fence / not-leader / transport) drops the
        suspect broker's producer and re-resolves; the records then
        re-dispatch fresh on the new leader — identical semantics to the
        synchronous ``_routed_attempts`` reroute, one attempt per call (the
        publisher's stash-and-retry ladder provides the outer loop)."""
        ih = handle.inner
        if not ih.future.done():
            raise TransactionStateError("pipelined commit still in flight")
        exc = None if ih.future.cancelled() else ih.future.exception()
        rerouted = isinstance(exc, _REROUTE_ERRORS)
        if rerouted:
            self._inner.pop(handle.addr, None)
            self._router.invalidate_partition("", handle.partition,
                                              suspect=handle.addr)
        addr = self._router.leader_for(handle.partition, refresh=rerouted)
        inner = self._inner_for(addr)
        if addr == handle.addr and inner is ih.producer:
            inner.retry_pipelined(ih)
            return handle
        if getattr(inner, "in_transaction", False):
            inner.abort()
        inner.begin()
        for r in ih.records:
            inner.send(r)
        handle.inner = inner.commit_pipelined()
        handle.addr = addr
        return handle

    def _partition_of(self, records: Sequence[LogRecord]) -> int:
        parts = {r.partition for r in records}
        if len(parts) > 1:
            # a cross-partition batch routes by its FIRST record; the broker
            # refuses if the partitions live on different leaders (the
            # engine's lanes are single-partition, so this is the raw-client
            # edge case, surfaced loudly by the broker's per-partition gate)
            logger.debug("routed batch spans partitions %s; routing by the "
                         "first", sorted(parts))
        return records[0].partition if records else 0

    def _routed(self, records: Sequence[LogRecord], op: str):
        """Run one producer operation on the partition's current leader,
        re-resolving the leader between attempts — a retried commit carries
        the SAME records (and, on the same broker, the same txn_seq), so the
        broker-plane dedup/alias machinery keeps it exactly-once wherever
        the partition landed. Traced callers get a ``router.commit`` span
        around the whole ladder (redirect events per rerouted attempt), with
        the inner broker-call spans chained under it."""
        partition = self._partition_of(records)
        span = _router_span(self._router.tracer, "router.commit",
                            partition=partition, op=op)
        if span is None:
            return self._routed_attempts(records, op, partition, None)
        with span:  # records exceptions + finishes
            return self._routed_attempts(records, op, partition, span)

    def _routed_attempts(self, records: Sequence[LogRecord], op: str,
                         partition: int, span):
        last: Optional[BaseException] = None
        backoff = 0.05
        for attempt in range(self._attempts):
            addr = self._router.leader_for(partition,
                                           refresh=attempt > 0)
            try:
                inner = self._inner.get(addr)
                if inner is None or inner.fenced:
                    inner = self._router._child(addr).transactional_producer(
                        self.transactional_id)
                    self._inner[addr] = inner
                if span is not None:
                    span.set_attribute("broker", addr)
                    span.set_attribute("attempts", attempt + 1)
                if op == "send_immediate":
                    return inner.send_immediate(records[0])
                inner.begin()
                for r in records:
                    inner.send(r)
                if op == "commit_unsequenced":
                    return inner.commit_unsequenced()
                return inner.commit()
            except TransactionStateError:
                raise
            except _REROUTE_ERRORS as exc:
                last = exc
                if span is not None:
                    span.add_event("redirect", {
                        "attempt": attempt, "from": addr,
                        "error": type(exc).__name__})
                self._inner.pop(addr, None)
                self._router.invalidate_partition("", partition,
                                                  suspect=addr)
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.5)
        self._fenced = True
        if last is not None:
            if isinstance(last, ProducerFencedError):
                raise last
            raise ProducerFencedError(
                f"no leader for partition {partition} after "
                f"{self._attempts} routed attempts: {last!r}")
        raise ProducerFencedError(
            f"no leader for partition {partition} (empty membership?)")


class PartitionRouter:
    """LogTransport-protocol client over a spread cluster (module doc)."""

    is_remote = True

    def __init__(self, targets, config=None, tracer=None,
                 metrics=None) -> None:
        if isinstance(targets, str):
            self.bootstrap = [t.strip() for t in targets.split(",")
                              if t.strip()]
        else:
            self.bootstrap = [t for t in targets if t]
        if not self.bootstrap:
            raise ValueError("need at least one bootstrap broker target")
        self._config = config
        self.tracer = tracer
        self.metrics = metrics
        self._lock = threading.Lock()
        self._children: Dict[str, GrpcLogTransport] = {}
        self._meta: dict = {}
        self._meta_stale = True
        #: per-partition leader cache WITH invalidation: a redirect or a
        #: connect failure evicts the entry (and marks the whole map stale),
        #: so a moved-back partition never ping-pongs through a dead broker
        self._leader_cache: Dict[int, str] = {}
        self._topics: Dict[str, TopicSpec] = {}

    # -- metadata -------------------------------------------------------------------------

    def _child(self, addr: str) -> GrpcLogTransport:
        with self._lock:
            child = self._children.get(addr)
            if child is None:
                child = GrpcLogTransport(addr, config=self._config,
                                         tracer=self.tracer,
                                         metrics=self.metrics)
                self._children[addr] = child
        return child

    def _drop_child(self, addr: str) -> None:
        with self._lock:
            child = self._children.pop(addr, None)
        if child is not None:
            try:
                child.close()
            except Exception:  # noqa: BLE001 — already broken
                pass

    def refresh_meta(self, force: bool = False) -> dict:
        """Fetch the cluster metadata view from the coordinator (preferred)
        or any reachable member/bootstrap broker. Traced callers get a
        ``router.resolve`` span around the actual fetch (cache hits stay
        span-free — resolve cost, not cache reads, is the anatomy leg)."""
        with self._lock:
            if self._meta and not self._meta_stale and not force:
                return self._meta
            meta = dict(self._meta)
        span = _router_span(self.tracer, "router.resolve")
        if span is None:
            return self._refresh_meta_fetch(meta)
        with span:  # records exceptions + finishes
            fresh = self._refresh_meta_fetch(meta)
            span.set_attribute("coordinator", fresh.get("coordinator", ""))
            return fresh

    def _refresh_meta_fetch(self, meta: dict) -> dict:
        sources: List[str] = []
        for addr in ([meta.get("coordinator", "")]
                     + list(meta.get("members", ())) + self.bootstrap):
            if addr and addr not in sources:
                sources.append(addr)
        last: Optional[BaseException] = None
        for addr in sources:
            try:
                fresh = self._child(addr).cluster_meta()
            except Exception as exc:  # noqa: BLE001 — try the next member
                last = exc
                self._drop_child(addr)
                continue
            # prefer the coordinator's own answer: a member's cached view
            # is good enough to route by, but one more hop gets authority
            coord = fresh.get("coordinator", "")
            if coord and coord != addr:
                try:
                    fresh = self._child(coord).cluster_meta()
                except Exception:  # noqa: BLE001 — member view still usable
                    self._drop_child(coord)
            with self._lock:
                self._meta = fresh
                self._meta_stale = False
                self._leader_cache = {
                    int(k): str(v) for k, v in
                    (fresh.get("assignments") or {}).items()}
            return fresh
        raise RuntimeError(
            f"no cluster member reachable for metadata: {last!r}")

    def leader_for(self, partition: int, refresh: bool = False) -> str:
        """The partition's current leader address (assignment map, falling
        back to the coordinator for unassigned indices)."""
        if refresh:
            with self._lock:
                self._meta_stale = True
        with self._lock:
            hit = None if self._meta_stale else \
                self._leader_cache.get(partition)
            coord = self._meta.get("coordinator", "")
        if hit:
            return hit
        meta = self.refresh_meta()
        addr = (meta.get("assignments") or {}).get(str(partition))
        return addr or meta.get("coordinator") or coord or self.bootstrap[0]

    def invalidate_partition(self, topic: str, partition: int,
                             suspect: str = "") -> None:
        """Evict one partition's cached leader (a redirect or connect
        failure proved it wrong); the next resolve re-fetches the map."""
        del topic  # assignment unit is the partition index
        with self._lock:
            self._leader_cache.pop(partition, None)
            self._meta_stale = True

    def cluster_meta(self, op: str = "status", **payload) -> dict:
        """Pass-through to the coordinator's ClusterMeta plane (mutations
        route there; status is answered from any member)."""
        if op == "status":
            return self.refresh_meta(force=True)
        meta = self.refresh_meta()
        coord = meta.get("coordinator") or self.bootstrap[0]
        out = self._child(coord).cluster_meta(op, **payload)
        with self._lock:
            self._meta_stale = True
        return out

    def _coordinator_child(self) -> GrpcLogTransport:
        meta = self.refresh_meta()
        return self._child(meta.get("coordinator") or self.bootstrap[0])

    # -- LogTransport protocol ------------------------------------------------------------

    def create_topic(self, spec: TopicSpec) -> None:
        self._coordinator_child().create_topic(spec)
        with self._lock:
            self._topics[spec.name] = spec

    def topic(self, name: str) -> TopicSpec:
        with self._lock:
            hit = self._topics.get(name)
        if hit is not None:
            return hit
        spec = self._coordinator_child().topic(name)
        with self._lock:
            self._topics[name] = spec
        return spec

    def num_partitions(self, name: str) -> int:
        return self.topic(name).partitions

    def transactional_producer(self, transactional_id: str) -> RoutedProducer:
        return RoutedProducer(self, transactional_id)

    def read(self, topic: str, partition: int, from_offset: int = 0,
             max_records: Optional[int] = None,
             isolation: str = "read_committed") -> Sequence[LogRecord]:
        return self._routed_call(partition, lambda c: c.read(
            topic, partition, from_offset=from_offset,
            max_records=max_records, isolation=isolation))

    def end_offset(self, topic: str, partition: int,
                   isolation: str = "read_committed") -> int:
        return self._routed_call(partition, lambda c: c.end_offset(
            topic, partition, isolation=isolation))

    def high_watermark(self, topic: str, partition: int) -> int:
        return self._routed_call(
            partition, lambda c: c.high_watermark(topic, partition))

    def latest_by_key(self, topic: str, partition: int,
                      isolation: str = "read_committed"
                      ) -> Mapping[str, LogRecord]:
        return self._routed_call(partition, lambda c: c.latest_by_key(
            topic, partition, isolation=isolation))

    async def wait_for_append(self, topic: str, partition: int,
                              after_offset: int) -> None:
        import asyncio

        loop = asyncio.get_running_loop()
        last: Optional[BaseException] = None
        for attempt in range(3):
            # resolve OFF the event loop: a refresh is a blocking metadata
            # RPC, and this coroutine runs on the engine's loop
            addr = await loop.run_in_executor(
                None, lambda a=attempt: self.leader_for(partition, a > 0))
            try:
                await self._child(addr).wait_for_append(
                    topic, partition, after_offset)
                return
            except grpc.RpcError as exc:
                last = exc
                self.invalidate_partition("", partition, suspect=addr)
        raise last if last is not None else RuntimeError("unreachable")

    def _routed_call(self, partition: int, op):
        """Run one read-side operation on the partition's current leader,
        re-resolving (and invalidating the cached hint) when the ACTUAL
        call fails — a reader must recover from a dead or moved leader
        exactly like a producer does, not keep hitting its corpse. Traced
        callers get a ``router.call`` span (redirect events per retry)."""
        span = _router_span(self.tracer, "router.call", partition=partition)
        if span is None:
            return self._routed_call_attempts(partition, op, None)
        with span:  # records exceptions + finishes
            return self._routed_call_attempts(partition, op, span)

    def _routed_call_attempts(self, partition: int, op, span):
        last: Optional[BaseException] = None
        for attempt in range(3):
            addr = self.leader_for(partition, refresh=attempt > 0)
            try:
                return op(self._child(addr))
            except grpc.RpcError as exc:
                last = exc
                if span is not None:
                    span.add_event("redirect", {"attempt": attempt,
                                                "from": addr})
                self.invalidate_partition("", partition, suspect=addr)
        raise last if last is not None else RuntimeError("unreachable")

    def close(self) -> None:
        with self._lock:
            children, self._children = list(self._children.values()), {}
        for child in children:
            try:
                child.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
