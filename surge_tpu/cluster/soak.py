"""Sustained seeded chaos soak: prove the cluster heals itself.

One soak run builds a 3+-broker spread cluster in-process, drives Zipf
hot-key-skewed writers through the :class:`PartitionRouter`, and executes a
seeded schedule of faults while the autobalancer runs its decision cycles:

- **rolling kills** — a broker (the coordinator on odd seeds, a partition
  leader on even) is hard-killed mid-run and relit over the same log later;
- **link faults** — a seeded fault plan (ship drops + Transact reorders) is
  armed on a surviving broker (the ``file`` backend arms fsync hiccups too);
- **membership churn** — a fresh broker catch-up-syncs through the slice
  lane, joins via AddBroker, and is RemoveBroker'd again before the end;
- **skew** — keys are Zipf-distributed, so one partition runs hot.

Scoring is the PR-9 telemetry plane itself: a FederatedScraper pulls every
broker (a dead one answers ``up{instance}=0``), the SLO engine burns the
``fleet-up`` objective on tight windows, and the verdict demands that

1. every acked commit appears **exactly once** in the final logs (and no
   payload, acked or in-doubt, appears twice),
2. every partition converges to **exactly one leader** the whole fleet
   agrees on,
3. every SLO page raised during a fault **clears** after the heal,
4. the autobalancer's decisions are reconstructable from the **merged
   flight timeline** (broker + fleet + balancer recorders).

``run_soak(seed)`` returns the verdict dict; ``tests/test_cluster_selfheal``
runs the 3-seed fast variant in tier-1 and ``SURGE_BENCH_SOAK=1 python
bench.py`` the long randomized one.

``run_saga_soak(seed)`` is the saga-storm arm (ISSUE 19): two engines (the
saga family + a counter "acct" participant) ride the same router over the
same chaos schedule — rolling kill, link faults, a mid-storm SagaManager
restart — while a storm of two-step transfer sagas (a seeded fraction
poisoned into the compensation walk) runs to terminal states. Its verdict
is **0 lost / 0 duplicated / 0 half-compensated**: every acked saga reaches
a terminal row, every account's balance equals the sum the saga rows' own
committed/compensated masks predict, and the ledger-reconciliation
invariant holds over every row. ``tests/test_saga_soak`` runs the 3-seed
fast variant in tier-1 and ``SURGE_BENCH_SAGA=1 python bench.py`` the
storm.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Dict, List, Optional

from surge_tpu.common import logger
from surge_tpu.config import Config
from surge_tpu.log import (
    GrpcLogTransport,
    InMemoryLog,
    LogRecord,
    LogServer,
    TopicSpec,
)
from surge_tpu.log.transport import NotLeaderError, ProducerFencedError

__all__ = ["run_soak", "run_saga_soak"]

TOPIC = "ev"


def _soak_config(extra: Optional[dict] = None) -> Config:
    overrides = {
        "surge.log.replication-ack-timeout-ms": 1_500,
        "surge.log.replication-isr-timeout-ms": 600,
        "surge.log.failover.probe-interval-ms": 150,
        "surge.log.failover.probe-failures": 2,
        "surge.log.quorum.vote-timeout-ms": 600,
        "surge.log.quorum.vote-rounds": 8,
        "surge.log.replication.min-insync-acks": 2,
        "surge.cluster.reassign-grace-ms": 1_200,
        "surge.cluster.balancer.interval-ms": 400,
        "surge.cluster.balancer.move-budget": 8,
        "surge.cluster.balancer.window-ms": 20_000,
        "surge.cluster.balancer.hysteresis-ms": 2_000,
        "surge.cluster.balancer.max-lead-skew": 1,
        "surge.slo.fast-window-ms": 1_200,
        "surge.slo.slow-window-ms": 3_000,
    }
    overrides.update(extra or {})
    return Config(overrides=overrides)


def _free_ports(n: int) -> List[int]:
    import socket

    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def _zipf_partition(rng: random.Random, partitions: int) -> int:
    """Zipf-ish hot-key skew: partition 0 is the hot one (~1/H weight of
    rank 1), the tail decays as 1/rank."""
    weights = [1.0 / (rank + 1) for rank in range(partitions)]
    return rng.choices(range(partitions), weights=weights, k=1)[0]


class _Fleet:
    """The soak's broker pool: live LogServer objects by address (relights
    replace entries in place; the scraper's fetch closures read through)."""

    def __init__(self, addrs: List[str], cfg: Config) -> None:
        self.cfg = cfg
        self.addrs = list(addrs)
        self.live: Dict[str, LogServer] = {}
        self.flights: Dict[str, object] = {}

    def start_initial(self) -> None:
        leader_addr, follower_addrs = self.addrs[0], self.addrs[1:]
        for addr in follower_addrs:
            server = LogServer(InMemoryLog(),
                               port=int(addr.rsplit(":", 1)[1]),
                               follower_of=leader_addr, auto_promote=True,
                               config=self.cfg, quorum_peers=self.addrs)
            server.start()
            self.live[addr] = server
            self.flights[addr] = server.flight
        leader = LogServer(InMemoryLog(),
                           port=int(leader_addr.rsplit(":", 1)[1]),
                           replicate_to=follower_addrs, config=self.cfg,
                           quorum_peers=self.addrs, auto_promote=True)
        leader.start()
        self.live[leader_addr] = leader
        self.flights[leader_addr] = leader.flight

    def scrape_target(self, addr: str):
        from surge_tpu.observability import ScrapeTarget

        def fetch() -> str:
            server = self.live.get(addr)
            if server is None or server._dead:
                raise RuntimeError(f"{addr} is down")
            return server.metrics_text()

        return ScrapeTarget(instance=addr, role="broker", fetch=fetch)

    def kill(self, addr: str) -> List[int]:
        server = self.live[addr]
        led = server.partitions_led()
        server.kill()
        if server.kill_done is not None:
            server.kill_done.wait(10)
        return led

    def relight(self, addr: str, follower_of: str) -> LogServer:
        old = self.live[addr]
        server = LogServer(old.log, port=int(addr.rsplit(":", 1)[1]),
                           follower_of=follower_of, auto_promote=True,
                           config=self.cfg, quorum_peers=self.addrs,
                           flight=old.flight)  # one story per broker
        server.start()
        self.live[addr] = server
        return server

    def coordinator(self) -> Optional[str]:
        for addr, server in self.live.items():
            if server.role == "leader" and not server._dead:
                return addr
        return None

    def admin(self, op: str, timeout: float = 20.0, **payload) -> dict:
        """Run a ClusterMeta mutation against the CURRENT coordinator,
        riding out elections."""
        deadline = time.monotonic() + timeout
        last: Optional[BaseException] = None
        while time.monotonic() < deadline:
            coord = self.coordinator()
            if coord is not None:
                client = GrpcLogTransport(coord, config=self.cfg)
                try:
                    return client.cluster_meta(op, **payload)
                except Exception as exc:  # noqa: BLE001 — mid-election
                    last = exc
                finally:
                    client.close()
            time.sleep(0.2)
        raise TimeoutError(f"ClusterMeta {op} never reached a coordinator: "
                           f"{last!r}")

    def stop_all(self) -> None:
        for server in self.live.values():
            try:
                server.stop()
            except Exception:  # noqa: BLE001 — already killed
                pass


def _writer(fleet: _Fleet, router, seed: int, w: int, stop: threading.Event,
            partitions: int, ledger: list, lock: threading.Lock,
            errors: list) -> None:
    rng = random.Random(seed * 1009 + w)
    producer = None
    i = 0
    try:
        while not stop.is_set():
            p = _zipf_partition(rng, partitions)
            payload = f"s{seed}-w{w}-{i}-p{p}".encode()
            deadline = time.monotonic() + 30.0
            grace = None
            while True:
                if stop.is_set():
                    # drain: one short grace to resolve the in-flight
                    # payload, then leave it in-doubt (uniqueness is still
                    # verified — only the ack ledger excludes it)
                    if grace is None:
                        grace = time.monotonic() + 2.0
                    if time.monotonic() > grace:
                        return
                if time.monotonic() > deadline:
                    return  # in-doubt: never acked
                try:
                    if producer is None:
                        producer = router.transactional_producer(
                            f"soak-{seed}-w{w}")
                    producer.begin()
                    producer.send(LogRecord(
                        topic=TOPIC, key=f"k{w}-{rng.randrange(8)}",
                        value=payload, partition=p))
                    producer.commit()
                    with lock:
                        ledger.append((p, payload))
                    break
                except (ProducerFencedError, NotLeaderError):
                    producer = None
                except Exception:  # noqa: BLE001 — broker mid-failover
                    if producer is not None and producer.in_transaction:
                        producer.abort()
                    time.sleep(0.05)
            i += 1
            time.sleep(0.002)
    except Exception as exc:  # noqa: BLE001 — a dead writer fails the soak
        errors.append(repr(exc))


def run_soak(seed: int, brokers: int = 3, partitions: int = 4,
             seconds: float = 8.0, writers: int = 3,
             membership_churn: bool = True,
             config_extra: Optional[dict] = None) -> dict:
    """One seeded chaos schedule; returns the verdict dict (see module
    doc). Raises nothing on a failed verdict — callers assert on the
    fields, so a failing soak reports everything it measured."""
    from surge_tpu.cluster.autobalancer import Autobalancer
    from surge_tpu.cluster.router import PartitionRouter
    from surge_tpu.observability import (FederatedScraper, FlightRecorder,
                                         SLO, SLOEngine, merge_dumps)

    rng = random.Random(seed)
    cfg = _soak_config(config_extra)
    addrs = [f"127.0.0.1:{p}" for p in _free_ports(brokers + 1)]
    join_addr, addrs = addrs[-1], addrs[:-1]
    fleet = _Fleet(addrs, cfg)
    fleet.start_initial()
    router = None
    balancer = None
    scraper = None
    joiner = None
    stop = threading.Event()
    threads: List[threading.Thread] = []
    try:
        setup = GrpcLogTransport(addrs[0], config=cfg)
        setup.create_topic(TopicSpec(TOPIC, partitions))
        setup.cluster_meta("spread", partitions=partitions)
        setup.close()

        # telemetry plane: federated scrape over in-process fetchers + the
        # SLO engine on tight windows; its flight ring carries the pages
        fleet_flight = FlightRecorder(name="fleet", role="engine")
        scraper = FederatedScraper(
            [fleet.scrape_target(a) for a in addrs], config=cfg)
        scraper.slo = SLOEngine(
            [SLO("fleet-up", family="up", kind="bound", objective=0.99,
                 threshold=1.0, op="lt",
                 description="every member answers its scrape")],
            config=cfg, metrics=scraper.metrics, flight=fleet_flight)
        balancer_flight = FlightRecorder(name="autobalancer",
                                         role="balancer")
        balancer = Autobalancer(scraper, addrs, config=cfg,
                                flight=balancer_flight)

        router = PartitionRouter(",".join(addrs), config=cfg)
        ledger: list = []
        ledger_lock = threading.Lock()
        writer_errors: list = []
        for w in range(writers):
            t = threading.Thread(
                target=_writer,
                args=(fleet, router, seed, w, stop, partitions, ledger,
                      ledger_lock, writer_errors),
                daemon=True)
            t.start()
            threads.append(t)

        # the seeded schedule
        t0 = time.monotonic()
        kill_at = t0 + 0.22 * seconds
        relight_at = t0 + 0.55 * seconds
        join_at = t0 + 0.62 * seconds
        remove_at = t0 + 0.88 * seconds
        end_at = t0 + seconds
        kill_coordinator = bool(seed % 2)
        victim: Optional[str] = None
        victim_led: List[int] = []
        faulted: Optional[str] = None
        relit = False
        joined = False
        removed = not membership_churn
        # link faults on one non-victim broker, seeded
        fault_plan = json.dumps({"rules": [
            {"site": "ship.*", "action": "drop", "p": 0.08, "times": None},
            {"site": "rpc.Transact", "action": "reorder", "p": 0.08,
             "times": None, "delay_ms": 15.0},
        ]})
        while time.monotonic() < end_at:
            now = time.monotonic()
            if victim is None and now >= kill_at:
                coord = fleet.coordinator() or addrs[0]
                if kill_coordinator:
                    victim = coord
                else:
                    others = [a for a in addrs if a != coord]
                    victim = others[rng.randrange(len(others))]
                survivors = [a for a in addrs if a != victim]
                faulted = survivors[rng.randrange(len(survivors))]
                client = GrpcLogTransport(faulted, config=cfg)
                try:
                    client.arm_faults(fault_plan, seed=seed)
                finally:
                    client.close()
                victim_led = fleet.kill(victim)
                logger.warning("soak %d: killed %s (coordinator=%s, led "
                               "%s); faults armed on %s", seed, victim,
                               kill_coordinator, victim_led, faulted)
            if victim is not None and not relit and now >= relight_at:
                follower_of = fleet.coordinator() or \
                    [a for a in addrs if a != victim][0]
                fleet.relight(victim, follower_of)
                relit = True
            if membership_churn and not joined and now >= join_at:
                coord = fleet.coordinator()
                if coord is not None:
                    joiner = LogServer(
                        InMemoryLog(),
                        port=int(join_addr.rsplit(":", 1)[1]),
                        follower_of=coord, auto_promote=True, config=cfg)
                    joiner.catch_up(coord)
                    joiner.start()
                    fleet.live[join_addr] = joiner
                    fleet.flights[join_addr] = joiner.flight
                    fleet.admin("add", addr=join_addr)
                    joined = True
            if joined and not removed and now >= remove_at:
                fleet.admin("remove", addr=join_addr)
                removed = True
            try:
                balancer.cycle()
            except Exception:  # noqa: BLE001 — a cycle must not end the soak
                logger.exception("soak balancer cycle failed")
            time.sleep(0.15)
        if joined and not removed:
            fleet.admin("remove", addr=join_addr)
        # settle: writers drain, faults disarm, the balancer converges
        stop.set()
        for t in threads:
            t.join(45.0)
        if faulted is not None and not fleet.live[faulted]._dead:
            client = GrpcLogTransport(faulted, config=cfg)
            try:
                client.disarm_faults()
            except Exception:  # noqa: BLE001 — faulted broker died
                pass
            finally:
                client.close()
        settle_deadline = time.monotonic() + 25.0
        converged = False
        while time.monotonic() < settle_deadline:
            try:
                decision = balancer.cycle()
            except Exception:  # noqa: BLE001
                decision = {}
            verdict_leaders = _leader_verdict(fleet, addrs, partitions)
            if (verdict_leaders["ok"] and not scraper.slo.breached()
                    and decision.get("decision") == "skip"
                    and decision.get("reason") in ("within-skew",
                                                   "fewer-than-2-up-members")):
                # healed AND balanced: exactly one live leader per
                # partition, no open pages, and the balancer itself reports
                # the spread back within its skew bound
                converged = True
                break
            time.sleep(0.3)
        # final verdicts
        leaders = _leader_verdict(fleet, addrs, partitions)
        lost, duplicated, acked = _ledger_verdict(fleet, cfg, ledger,
                                                  partitions)
        pages = _page_verdict(fleet_flight)
        dumps = [f.dump() for f in fleet.flights.values()]
        dumps += [fleet_flight.dump(), balancer_flight.dump()]
        timeline = merge_dumps(dumps)
        balance_events = [e for e in timeline
                          if str(e.get("type", "")).startswith("balance.")]
        heal_events = [e for e in timeline if e.get("type") in
                       ("broker.kill", "cluster.reassign", "quorum.win",
                        "role.promote", "handoff.partition.done",
                        "cluster.add", "cluster.remove", "isr.rejoin",
                        "cluster.meta-apply", "slo.breach",
                        "slo.recovered")]
        return {
            "seed": seed,
            "acked_commits": acked,
            "lost": lost,
            "duplicated": duplicated,
            "writer_errors": writer_errors,
            "leaders": leaders,
            "converged": converged,
            "slo_pages": pages,
            "membership_churn": joined and removed,
            "victim": victim,
            "victim_was_coordinator": kill_coordinator,
            "victim_led": victim_led,
            "balancer_decisions": len(balance_events),
            "balancer_moves": sum(
                1 for e in balance_events if e["type"] == "balance.moved"),
            "heal_events": [e["type"] for e in heal_events],
            "timeline_events": len(timeline),
        }
    finally:
        stop.set()
        if balancer is not None:
            balancer.stop_sync()
        if scraper is not None:
            scraper.stop()
        if router is not None:
            router.close()
        fleet.stop_all()


def _leader_verdict(fleet: _Fleet, addrs: List[str],
                    partitions: int) -> dict:
    """Exactly one leader per partition, agreed by every live broker, and
    that leader is alive."""
    claims: Dict[int, set] = {p: set() for p in range(partitions)}
    views = []
    for addr, server in fleet.live.items():
        if server._dead:
            continue
        status = server.broker_status()
        views.append((addr, status.get("assign_epoch", 0),
                      tuple(sorted((status.get("assignments") or {}).items()))))
        for p in status.get("partitions_led", ()):
            claims[int(p)].add(addr)
    problems = []
    for p, owners in claims.items():
        if len(owners) != 1:
            problems.append(f"partition {p} has {len(owners)} leaders: "
                            f"{sorted(owners)}")
        else:
            owner = next(iter(owners))
            if fleet.live.get(owner) is None or fleet.live[owner]._dead:
                problems.append(f"partition {p} led by dead {owner}")
    newest = max((v[1] for v in views), default=0)
    maps = {v[2] for v in views if v[1] == newest}
    if len(maps) > 1:
        problems.append("brokers at the newest assign epoch disagree on "
                        "the map")
    return {"ok": not problems, "problems": problems,
            "claims": {p: sorted(o) for p, o in claims.items()}}


def _ledger_verdict(fleet: _Fleet, cfg: Config, ledger: list,
                    partitions: int):
    """0 lost / 0 duplicated: every acked payload exactly once in the final
    log (read from each partition's current leader), and NO payload —
    acked or in-doubt — more than once."""
    by_partition: Dict[int, List[bytes]] = {p: [] for p in range(partitions)}
    for p, payload in ledger:
        by_partition[p].append(payload)
    lost = duplicated = 0
    meta = fleet.admin("status")
    for p in range(partitions):
        owner = (meta.get("assignments") or {}).get(str(p)) \
            or meta.get("coordinator")
        server = fleet.live.get(owner)
        if server is None or server._dead:
            lost += len(by_partition[p])
            continue
        values = [r.value for r in server.log.read(TOPIC, p)]
        counts: Dict[bytes, int] = {}
        for v in values:
            counts[v] = counts.get(v, 0) + 1
        for payload in by_partition[p]:
            n = counts.get(payload, 0)
            if n == 0:
                lost += 1
            elif n > 1:
                duplicated += 1
        # in-doubt payloads must not appear twice either
        duplicated += sum(1 for v, n in counts.items()
                          if n > 1 and v not in by_partition[p])
    return lost, duplicated, len(ledger)


def _page_verdict(fleet_flight) -> dict:
    """Every SLO page raised during a fault must CLEAR after the heal."""
    events = fleet_flight.events()
    raised = [e for e in events if e.get("type") == "slo.breach"]
    open_pages: Dict[str, int] = {}
    for e in events:
        if e.get("type") == "slo.breach":
            open_pages[e.get("objective", "?")] = \
                open_pages.get(e.get("objective", "?"), 0) + 1
        elif e.get("type") == "slo.recovered":
            open_pages.pop(e.get("objective", "?"), None)
    return {"raised": len(raised), "still_open": sorted(open_pages),
            "cleared": not open_pages}


# -- the saga-storm arm --------------------------------------------------------------


def _transfer_definition():
    """The storm's two-step money move.

    Targets ride the saga id itself (``x{seed}:{src}:{dst}:{n}``) so a
    restarted manager rebuilds every factory input from replayed state
    alone; a poisoned context slot (``c1 >= 1``) turns the credit into a
    command the counter model REJECTS, forcing the reverse compensation
    walk over the already-committed debit.
    """
    from surge_tpu.models import counter
    from surge_tpu.saga import SagaDefinition, SagaStep

    def _src(sid, s):
        return sid.split(":")[1]

    def _dst(sid, s):
        return sid.split(":")[2]

    return SagaDefinition(
        name="transfer", def_id=1,
        steps=(
            SagaStep("debit", participant="acct", target=_src,
                     command=lambda tid, s: counter.Decrement(tid),
                     compensation=lambda tid, s: counter.Increment(tid)),
            SagaStep("credit", participant="acct", target=_dst,
                     command=lambda tid, s: (
                         counter.FailCommandProcessing(tid, "credit poisoned")
                         if s.c1 >= 1.0 else counter.Increment(tid)),
                     compensation=lambda tid, s: counter.Decrement(tid)),
        ))


def run_saga_soak(seed: int, brokers: int = 3, partitions: int = 4,
                  seconds: float = 6.0, sagas: int = 36,
                  accounts: int = 12, poison_fraction: float = 0.3,
                  manager_restart: bool = True, settle_s: float = 35.0,
                  config_extra: Optional[dict] = None) -> dict:
    """One seeded saga-storm schedule; returns the verdict dict.

    Like :func:`run_soak` this raises nothing on a failed verdict — the
    caller asserts on ``lost`` / ``duplicated`` / ``half_compensated`` so a
    failing storm still reports everything it measured, including the
    per-account ledger mismatches and the merged flight timeline counts.
    """
    import asyncio

    from surge_tpu import SurgeCommandBusinessLogic, create_engine
    from surge_tpu.cluster.autobalancer import Autobalancer
    from surge_tpu.cluster.router import PartitionRouter
    from surge_tpu.models import counter
    from surge_tpu.observability import (FederatedScraper, FlightRecorder,
                                         SLO, SLOEngine, merge_dumps)
    from surge_tpu.saga import TERMINAL, SagaManager, make_saga_logic
    from surge_tpu.testing.faults import FaultPlane
    from surge_tpu.testing.support import ZipfKeys

    rng = random.Random(seed)
    cfg = _soak_config({
        "surge.engine.num-partitions": partitions,
        "surge.producer.flush-interval-ms": 5,
        "surge.producer.ktable-check-interval-ms": 5,
        "surge.state-store.commit-interval-ms": 20,
        "surge.aggregate.init-retry-interval-ms": 5,
        "surge.replay.restore-on-start": False,
        "surge.saga.step-timeout-ms": 8_000,
        "surge.saga.step-max-attempts": 8,
        "surge.saga.step-backoff-ms": 60,
        "surge.saga.compensation-max-attempts": 8,
        "surge.saga.poll-interval-ms": 25,
        **(config_extra or {}),
    })
    addrs = [f"127.0.0.1:{p}" for p in _free_ports(brokers)]
    fleet = _Fleet(addrs, cfg)
    fleet.start_initial()
    router = None
    scraper = None
    balancer = None
    try:
        setup = GrpcLogTransport(addrs[0], config=cfg)
        setup.cluster_meta("spread", partitions=partitions)
        setup.close()

        fleet_flight = FlightRecorder(name="fleet", role="engine")
        scraper = FederatedScraper(
            [fleet.scrape_target(a) for a in addrs], config=cfg)
        scraper.slo = SLOEngine(
            [SLO("fleet-up", family="up", kind="bound", objective=0.99,
                 threshold=1.0, op="lt",
                 description="every member answers its scrape")],
            config=cfg, metrics=scraper.metrics, flight=fleet_flight)
        balancer = Autobalancer(scraper, addrs, config=cfg,
                                flight=FlightRecorder(name="autobalancer",
                                                      role="balancer"))
        router = PartitionRouter(",".join(addrs), config=cfg)

        # the seeded storm plan, drawn up-front so asyncio interleaving
        # never perturbs the sequence a seed produces
        keys = ZipfKeys(random.Random(seed * 31 + 7), n=accounts,
                        prefix="acct-")
        plan: List[tuple] = []
        for n in range(sagas):
            a = keys.draw()
            b = keys.draw()
            while b == a:
                b = keys.draw()
            poison = 1.0 if rng.random() < poison_fraction else 0.0
            plan.append((f"x{seed}:{a}:{b}:{n}", poison))
        kill_coordinator = bool(seed % 2)

        async def scenario() -> dict:
            saga_eng = acct_eng = None
            victim = faulted = None
            try:
                saga_eng = create_engine(make_saga_logic(), log=router,
                                         config=cfg)
                acct_eng = create_engine(
                    SurgeCommandBusinessLogic(
                        aggregate_name="acct", model=counter.CounterModel(),
                        state_format=counter.state_formatting(),
                        event_format=counter.event_formatting()),
                    log=router, config=cfg)
                # saga.* delay sites widen the race windows the rid table
                # must close; the crash sites stay for the unit suite
                mgr = SagaManager(
                    saga_eng, [_transfer_definition()],
                    {"acct": acct_eng, "saga": saga_eng}, config=cfg,
                    faults=FaultPlane.from_spec(json.dumps({"rules": [
                        {"site": "saga.step.dispatch", "action": "delay",
                         "p": 0.08, "delay_ms": 15.0, "times": None},
                        {"site": "saga.compensation.dispatch",
                         "action": "delay", "p": 0.08, "delay_ms": 15.0,
                         "times": None},
                    ]}), seed=seed))
                saga_eng.register_saga_manager(mgr)
                await acct_eng.start()
                await saga_eng.start()

                acked: set = set()
                start_errors: list = []

                async def _start_one(sid: str, poison: float) -> None:
                    last: Optional[BaseException] = None
                    deadline = time.monotonic() + seconds + settle_s
                    while time.monotonic() < deadline:
                        try:
                            await mgr.start_saga(sid, "transfer",
                                                 (0.0, poison))
                            acked.add(sid)
                            return
                        except Exception as exc:  # noqa: BLE001 — mid-failover
                            last = exc
                            await asyncio.sleep(0.1)
                    start_errors.append((sid, repr(last)))

                # the seeded chaos schedule: starts pace over the first 60%,
                # kill at 25%, manager restart at 45%, relight at 60%
                t0 = time.monotonic()
                kill_at = t0 + 0.25 * seconds
                restart_at = t0 + 0.45 * seconds
                relight_at = t0 + 0.60 * seconds
                end_at = t0 + seconds
                gap = (0.6 * seconds) / max(len(plan), 1)
                fault_plan = json.dumps({"rules": [
                    {"site": "ship.*", "action": "drop", "p": 0.06,
                     "times": None},
                    {"site": "rpc.Transact", "action": "reorder", "p": 0.06,
                     "times": None, "delay_ms": 12.0},
                ]})
                starters: List[asyncio.Task] = []
                launched = 0
                relit = False
                mgr_restarted = not manager_restart
                while time.monotonic() < end_at or launched < len(plan):
                    now = time.monotonic()
                    while launched < len(plan) and now >= t0 + gap * launched:
                        sid, poison = plan[launched]
                        starters.append(asyncio.get_running_loop().create_task(
                            _start_one(sid, poison)))
                        launched += 1
                    if victim is None and now >= kill_at:
                        coord = fleet.coordinator() or addrs[0]
                        others = [a for a in addrs if a != coord]
                        victim = coord if kill_coordinator else \
                            others[rng.randrange(len(others))]
                        survivors = [a for a in addrs if a != victim]
                        faulted = survivors[rng.randrange(len(survivors))]
                        client = GrpcLogTransport(faulted, config=cfg)
                        try:
                            client.arm_faults(fault_plan, seed=seed)
                        finally:
                            client.close()
                        await asyncio.to_thread(fleet.kill, victim)
                        logger.warning(
                            "saga soak %d: killed %s (coordinator=%s); "
                            "link faults on %s", seed, victim,
                            kill_coordinator, faulted)
                    if not mgr_restarted and now >= restart_at:
                        # the recovery leg: a cold manager resumes every
                        # in-flight saga from replayed aggregate rows alone
                        await mgr.stop()
                        await mgr.start()
                        mgr_restarted = True
                    if victim is not None and not relit and now >= relight_at:
                        follower_of = fleet.coordinator() or \
                            [a for a in addrs if a != victim][0]
                        await asyncio.to_thread(fleet.relight, victim,
                                                follower_of)
                        relit = True
                    try:
                        await asyncio.to_thread(balancer.cycle)
                    except Exception:  # noqa: BLE001 — must not end the storm
                        logger.exception("saga soak balancer cycle failed")
                    await asyncio.sleep(0.1)

                # settle: disarm link faults, drain the starters, then kick
                # every non-terminal saga until the whole storm is terminal
                if faulted is not None and not fleet.live[faulted]._dead:
                    client = GrpcLogTransport(faulted, config=cfg)
                    try:
                        client.disarm_faults()
                    except Exception:  # noqa: BLE001 — faulted broker died
                        pass
                    finally:
                        client.close()
                for t in starters:
                    try:
                        await t
                    except Exception as exc:  # noqa: BLE001
                        start_errors.append(("starter", repr(exc)))
                settle_deadline = time.monotonic() + settle_s
                pending = sorted(acked)
                while time.monotonic() < settle_deadline:
                    snapshot = dict(mgr._all_states())
                    pending = [sid for sid in sorted(acked)
                               if sid not in snapshot
                               or snapshot[sid].status not in TERMINAL]
                    if not pending:
                        break
                    for sid in pending:
                        mgr.kick(sid)
                    await asyncio.sleep(0.25)

                # verdicts
                snapshot = dict(mgr._all_states())
                reconcile = mgr.reconcile()
                lost_sagas = set(pending)
                lost_sagas |= {sid for sid, _ in start_errors
                               if sid != "starter"}
                # expected ledger: the saga rows' own masks predict every
                # balance (committed-and-not-compensated step effects)
                expected: Dict[str, int] = {}
                for sid, st in snapshot.items():
                    if not sid.startswith(f"x{seed}:"):
                        continue
                    _, a, b, _ = sid.split(":")
                    keep = st.committed & ~st.compensated
                    if keep >> 0 & 1:
                        expected[a] = expected.get(a, 0) - 1
                    if keep >> 1 & 1:
                        expected[b] = expected.get(b, 0) + 1
                touched = sorted({acct for sid, _ in plan
                                  for acct in sid.split(":")[1:3]})
                mismatches: Dict[str, dict] = {}
                dup_units = 0
                for acct in touched:
                    actual = None
                    for _ in range(4):
                        try:
                            st = await acct_eng.aggregate_for(
                                acct).get_state()
                            actual = 0 if st is None else st.count
                            break
                        except Exception:  # noqa: BLE001 — transient
                            await asyncio.sleep(0.2)
                    exp = expected.get(acct, 0)
                    if actual != exp:
                        mismatches[acct] = {"expected": exp,
                                            "actual": actual}
                        dup_units += abs((actual or 0) - exp)

                dumps = [f.dump() for f in fleet.flights.values()]
                dumps += [fleet_flight.dump(), saga_eng.flight.dump(),
                          acct_eng.flight.dump()]
                timeline = merge_dumps(dumps)
                saga_events = [e for e in timeline
                               if str(e.get("type", "")).startswith("saga.")]
                resumed = max((int(e.get("resumed", 0)) for e in saga_events
                               if e.get("type") == "saga.manager.start"),
                              default=0)
                return {
                    "seed": seed,
                    "sagas": len(plan),
                    "started": len(acked),
                    "poisoned": sum(1 for _, p in plan if p >= 1.0),
                    "lost": len(lost_sagas),
                    "duplicated": dup_units,
                    "half_compensated": len(reconcile["violations"]),
                    "reconcile": reconcile,
                    "counts": reconcile["counts"],
                    "ledger_mismatches": mismatches,
                    "start_errors": start_errors,
                    "victim": victim,
                    "victim_was_coordinator": kill_coordinator,
                    "manager_restarted": mgr_restarted and manager_restart,
                    "manager_resumed": resumed,
                    "saga_events": len(saga_events),
                    "timeline_events": len(timeline),
                }
            finally:
                if saga_eng is not None:
                    await saga_eng.stop()  # stops the manager too
                if acct_eng is not None:
                    await acct_eng.stop()

        return asyncio.run(scenario())
    finally:
        if balancer is not None:
            balancer.stop_sync()
        if scraper is not None:
            scraper.stop()
        if router is not None:
            router.close()
        fleet.stop_all()
