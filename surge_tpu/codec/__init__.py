"""Event/state schema registry and event→tensor codec.

The reference's ``modules/serialization`` defines only a byte-level contract; replay there
is a Kafka Streams RocksDB restore (SURVEY.md §3.3). The TPU build replaces bulk restore
with a batched ``lax.scan`` fold over *tensor-encoded* events, so serialization gains a
second, tensor-level contract:

- :mod:`surge_tpu.codec.schema` — declarative schemas for event/state dataclasses
  (numeric fields only on the tensor path; dictionary-encode strings via :class:`Vocab`).
- :mod:`surge_tpu.codec.tensor` — struct-of-arrays encoding of ragged per-aggregate event
  logs into dense ``[B, T]`` columns + mask + type ids (tagged unions for heterogeneous
  event types), and the inverse for golden-value round-trip tests.
"""

from surge_tpu.codec.schema import (
    FieldSpec,
    EventSchema,
    StateSchema,
    SchemaRegistry,
    Vocab,
    event_fields_from_dataclass,
)
from surge_tpu.codec.tensor import (
    PAD_TYPE_ID,
    ColumnarEvents,
    EncodedEvents,
    columnar_to_batch,
    encode_events,
    encode_events_columnar,
    decode_events,
    encode_states,
    decode_states,
    bucket_lengths,
)

__all__ = [
    "FieldSpec",
    "EventSchema",
    "StateSchema",
    "SchemaRegistry",
    "Vocab",
    "event_fields_from_dataclass",
    "PAD_TYPE_ID",
    "ColumnarEvents",
    "EncodedEvents",
    "columnar_to_batch",
    "encode_events_columnar",
    "encode_events",
    "decode_events",
    "encode_states",
    "decode_states",
    "bucket_lengths",
]
