"""Declarative schemas mapping domain dataclasses to fixed tensor layouts.

Design: every event type a model emits is registered with a ``SchemaRegistry`` under a
small integer ``type_id``. The registry derives the *union column layout* — the sorted set
of (field name → dtype) across all registered event types — so a heterogeneous event
stream encodes as one struct-of-arrays batch with a ``type_ids`` column (tagged union,
SURVEY.md §5.7 "masked vmap for heterogeneous aggregate types").

Only numeric scalar fields ride the tensor path. Strings (aggregate ids, item names) are
dictionary-encoded on the host via :class:`Vocab` before encoding.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Mapping, Sequence, Type

import numpy as np

_DTYPE_FOR_ANNOTATION = {
    int: np.dtype(np.int32),
    float: np.dtype(np.float32),
    bool: np.dtype(np.bool_),
}


@dataclass(frozen=True)
class FieldSpec:
    """A single numeric field of an event/state schema.

    ``bits`` (optional) declares the field's wire width for the bit-packed transfer
    format (surge_tpu.codec.wire): an unsigned value in ``[0, 2**bits)``. Fields
    without ``bits`` ride the wire as full-width side columns. Only unsigned integer
    ranges can be packed; host→device transfer is the replay bottleneck
    (SURVEY.md §7 hard-part 2), so narrow event payloads should declare it.
    """

    name: str
    dtype: np.dtype
    bits: int | None = None

    def __post_init__(self) -> None:
        object.__setattr__(self, "dtype", np.dtype(self.dtype))
        if self.bits is not None:
            if self.dtype.kind not in "iub":
                raise TypeError(f"field {self.name}: bits= requires an integer/bool "
                                f"dtype, got {self.dtype}")
            if not 0 < self.bits <= 30:
                raise ValueError(f"field {self.name}: bits must be in [1, 30]")


def event_fields_from_dataclass(cls: type, overrides: Mapping[str, Any] | None = None,
                                exclude: Iterable[str] = (),
                                bits: Mapping[str, int] | None = None) -> tuple[FieldSpec, ...]:
    """Derive FieldSpecs from a dataclass's annotations (int→i32, float→f32, bool→bool).

    ``bits`` maps field names to wire bit widths (see :class:`FieldSpec`)."""
    overrides = dict(overrides or {})
    bits = dict(bits or {})
    excluded = set(exclude)
    specs = []
    for f in dataclasses.fields(cls):
        if f.name in excluded:
            continue
        if f.name in overrides:
            specs.append(FieldSpec(f.name, np.dtype(overrides[f.name]),
                                   bits=bits.get(f.name)))
            continue
        dt = _DTYPE_FOR_ANNOTATION.get(f.type if isinstance(f.type, type) else None)
        if dt is None:
            # string annotations (PEP 563) — resolve the common builtins textually
            dt = {"int": np.dtype(np.int32), "float": np.dtype(np.float32),
                  "bool": np.dtype(np.bool_)}.get(str(f.type))
        if dt is None:
            raise TypeError(
                f"{cls.__name__}.{f.name}: unsupported tensor field type {f.type!r}; "
                f"exclude it or dictionary-encode it (Vocab) first")
        specs.append(FieldSpec(f.name, dt, bits=bits.get(f.name)))
    return tuple(specs)


@dataclass(frozen=True)
class EventSchema:
    """One event type's layout: its type_id and the numeric fields it carries."""

    cls: type
    type_id: int
    fields: tuple[FieldSpec, ...]
    # host-side extraction: event -> field value (defaults to getattr)
    getter: Callable[[Any, str], Any] = getattr

    @property
    def field_names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.fields)


@dataclass(frozen=True)
class StateSchema:
    """Aggregate state layout: a flat record of numeric fields.

    The batched replay carry is a dict-of-arrays pytree ``{name: [B]}``; models' JAX folds
    read and write these columns. ``to_record``/``from_record`` bridge the scalar world.
    """

    cls: type
    fields: tuple[FieldSpec, ...]

    @property
    def field_names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.fields)

    def to_record(self, state: Any) -> dict[str, Any]:
        return {f.name: getattr(state, f.name) for f in self.fields}

    def from_record(self, record: Mapping[str, Any]) -> Any:
        kwargs = {}
        for f in self.fields:
            v = record[f.name]
            if isinstance(v, (np.generic, np.ndarray)):
                v = v.item() if np.ndim(v) == 0 else v
            if f.dtype.kind == "b":
                v = bool(v)
            elif f.dtype.kind in "iu":
                v = int(v)
            elif f.dtype.kind == "f":
                v = float(v)
            kwargs[f.name] = v
        from surge_tpu.codec.tensor import _construct

        return _construct(self.cls, kwargs)


class SchemaRegistry:
    """Registry of one model family's event types + state type.

    Equivalent role to the reference's read/write formatting bundle on
    ``SurgeGenericBusinessLogicTrait`` (commondsl/SurgeGenericBusinessLogicTrait.scala:16-64),
    extended with the tensor layout the TPU replay engine consumes.
    """

    def __init__(self) -> None:
        self._by_cls: dict[type, EventSchema] = {}
        self._by_id: dict[int, EventSchema] = {}
        self._state: StateSchema | None = None

    # -- registration -----------------------------------------------------------------
    def register_event(self, cls: type, *, type_id: int | None = None,
                       fields: Sequence[FieldSpec] | None = None,
                       overrides: Mapping[str, Any] | None = None,
                       exclude: Iterable[str] = (),
                       bits: Mapping[str, int] | None = None) -> EventSchema:
        if cls in self._by_cls:
            raise ValueError(f"event type {cls.__name__} already registered")
        tid = type_id if type_id is not None else len(self._by_id)
        if tid in self._by_id:
            raise ValueError(f"type_id {tid} already taken by {self._by_id[tid].cls.__name__}")
        fs = tuple(fields) if fields is not None else event_fields_from_dataclass(
            cls, overrides=overrides, exclude=exclude, bits=bits)
        schema = EventSchema(cls=cls, type_id=tid, fields=fs)
        self._by_cls[cls] = schema
        self._by_id[tid] = schema
        return schema

    def register_state(self, cls: type, *, fields: Sequence[FieldSpec] | None = None,
                       overrides: Mapping[str, Any] | None = None,
                       exclude: Iterable[str] = ()) -> StateSchema:
        fs = tuple(fields) if fields is not None else event_fields_from_dataclass(
            cls, overrides=overrides, exclude=exclude)
        self._state = StateSchema(cls=cls, fields=fs)
        return self._state

    # -- lookup -----------------------------------------------------------------------
    @property
    def state(self) -> StateSchema:
        if self._state is None:
            raise ValueError("no state schema registered")
        return self._state

    def schema_for(self, event: Any) -> EventSchema:
        return self.schema_for_cls(type(event))

    def schema_for_cls(self, cls: type) -> EventSchema:
        s = self._by_cls.get(cls)
        if s is None:
            raise KeyError(f"unregistered event type {cls.__name__}")
        return s

    def schema_for_id(self, type_id: int) -> EventSchema:
        return self._by_id[type_id]

    @property
    def event_schemas(self) -> tuple[EventSchema, ...]:
        return tuple(self._by_id[k] for k in sorted(self._by_id))

    @property
    def num_event_types(self) -> int:
        return (max(self._by_id) + 1) if self._by_id else 0

    def union_columns(self) -> tuple[FieldSpec, ...]:
        """The union layout: one column per distinct field name, dtype-promoted.

        ``bits`` merges to the max declared width, but only when *every* event type
        carrying the field declares one — a single undeclared use forces the column
        to full width (packing a value that overflows its bits would corrupt
        neighbours)."""
        merged: dict[str, np.dtype] = {}
        merged_bits: dict[str, int | None] = {}
        for schema in self.event_schemas:
            for f in schema.fields:
                if f.name in merged:
                    merged[f.name] = np.promote_types(merged[f.name], f.dtype)
                    old = merged_bits[f.name]
                    merged_bits[f.name] = (max(old, f.bits)
                                           if (old is not None and f.bits is not None)
                                           else None)
                else:
                    merged[f.name] = f.dtype
                    merged_bits[f.name] = f.bits
        return tuple(FieldSpec(n, merged[n], bits=merged_bits[n]) for n in sorted(merged))


class Vocab:
    """Host-side dictionary encoder for string fields (string → dense int code).

    Replay decodes of string-keyed fields (e.g. ShoppingCart item ids) happen through the
    same table. Code 0 is reserved for the empty/unknown string.
    """

    def __init__(self) -> None:
        self._codes: dict[str, int] = {"": 0}
        self._strings: list[str] = [""]

    def encode(self, s: str) -> int:
        code = self._codes.get(s)
        if code is None:
            code = len(self._strings)
            self._codes[s] = code
            self._strings.append(s)
        return code

    def decode(self, code: int) -> str:
        return self._strings[int(code)]

    def __len__(self) -> int:
        return len(self._strings)
