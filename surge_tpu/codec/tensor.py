"""Struct-of-arrays encoding of ragged per-aggregate event logs.

Layout (``EncodedEvents``), chosen for the TPU scan (SURVEY.md §7 "Event→tensor codec"):

- ``type_ids``: int32 ``[B, T]`` — tagged-union discriminant; ``PAD_TYPE_ID`` (-1) marks
  padding past each aggregate's log length.
- ``cols``: dict of ``[B, T]`` arrays, one per union column (see
  ``SchemaRegistry.union_columns``). Fields an event type lacks are zero-filled.
- ``lengths``: int32 ``[B]`` — true log lengths (mask = position < length).

B is the aggregate batch dimension (vmap/shard axis), T the time dimension (lax.scan
axis). Encoding is pure NumPy on the host; the replay engine moves arrays to device and
transposes to time-major itself.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

import numpy as np

from surge_tpu.codec.schema import SchemaRegistry, StateSchema

PAD_TYPE_ID = -1


@dataclass
class EncodedEvents:
    type_ids: np.ndarray  # [B, T] int32
    cols: dict[str, np.ndarray]  # each [B, T]
    lengths: np.ndarray  # [B] int32
    # union columns the producer declares derivable on device instead of stored/
    # transferred ({name: surge_tpu.codec.wire.DERIVE_*}); e.g. positional sequence
    # numbers ({"sequence_number": "ordinal"})
    derived_cols: dict[str, str] = field(default_factory=dict)

    @property
    def batch_size(self) -> int:
        return int(self.type_ids.shape[0])

    @property
    def max_len(self) -> int:
        return int(self.type_ids.shape[1])

    def mask(self) -> np.ndarray:
        """bool [B, T]: True where a real event exists."""
        return self.type_ids != PAD_TYPE_ID

    def nbytes(self) -> int:
        return self.type_ids.nbytes + self.lengths.nbytes + sum(c.nbytes for c in self.cols.values())


@dataclass
class ColumnarEvents:
    """Flat struct-of-arrays event log: N events across B aggregates, time-ordered
    within each aggregate. This is the *storage* layout (log segments are columnar so
    bulk replay never touches Python objects — SURVEY.md §7 hard-part "host-side
    encode"); :func:`columnar_to_batch` scatters it into the padded ``[B, T]`` batch
    with pure vectorized NumPy.

    - ``agg_idx``: int32 ``[N]`` — which aggregate (dense 0..B-1) each event belongs to.
    - ``type_ids``: int32 ``[N]``.
    - ``cols``: dict of ``[N]`` arrays (union columns; zero where a type lacks a field).
    """

    num_aggregates: int
    agg_idx: np.ndarray
    type_ids: np.ndarray
    cols: dict[str, np.ndarray]
    # columns the device derives instead of reading (see EncodedEvents.derived_cols)
    derived_cols: dict[str, str] = field(default_factory=dict)
    # optional aggregate-id strings, indexed by aggregate index 0..B-1 — carried by
    # segment chunks so bulk replay can write folded states back to the keyed store
    aggregate_ids: list[str] | None = None
    # global chunk ordinal within the source segment file (set by read_segment;
    # chunks are immutable once written, so this is a stable O(1) identity for
    # caches keyed per chunk)
    source_ordinal: int | None = None

    @property
    def num_events(self) -> int:
        return int(self.type_ids.shape[0])

    def nbytes(self) -> int:
        return (self.agg_idx.nbytes + self.type_ids.nbytes
                + sum(c.nbytes for c in self.cols.values()))

    def sorted_by_aggregate(self) -> "ColumnarEvents":
        """Events grouped by aggregate (stable: per-aggregate time order preserved),
        which makes :meth:`slice_aggregates` a contiguous O(1)-index slice."""
        if self.agg_idx.size and np.all(np.diff(self.agg_idx) >= 0):
            return self
        order = np.argsort(self.agg_idx, kind="stable")
        return ColumnarEvents(
            num_aggregates=self.num_aggregates, agg_idx=self.agg_idx[order],
            type_ids=self.type_ids[order],
            cols={k: v[order] for k, v in self.cols.items()},
            derived_cols=dict(self.derived_cols),
            aggregate_ids=self.aggregate_ids)

    def slice_aggregates(self, start: int, stop: int) -> "ColumnarEvents":
        """Sub-log for aggregates [start, stop). Requires aggregate-sorted order
        (see :meth:`sorted_by_aggregate`); re-indexes agg_idx to 0..(stop-start)."""
        lo, hi = np.searchsorted(self.agg_idx, (start, stop))
        return ColumnarEvents(
            num_aggregates=stop - start,
            agg_idx=self.agg_idx[lo:hi] - np.int32(start),
            type_ids=self.type_ids[lo:hi],
            cols={k: v[lo:hi] for k, v in self.cols.items()},
            derived_cols=dict(self.derived_cols),
            aggregate_ids=(None if self.aggregate_ids is None
                           else self.aggregate_ids[start:stop]))


def columnar_to_batch(colev: ColumnarEvents, pad_to: int | None = None) -> EncodedEvents:
    """Scatter a flat columnar log into the padded ``[B, T]`` batch. Fully vectorized
    (one stable argsort + one fancy-index scatter per column); no per-event Python."""
    b = colev.num_aggregates
    n = colev.num_events
    lengths = np.bincount(colev.agg_idx, minlength=b).astype(np.int32)
    t = int(pad_to) if pad_to is not None else int(lengths.max(initial=0))
    if lengths.size and int(lengths.max(initial=0)) > t:
        raise ValueError(f"pad_to={t} < longest log {int(lengths.max())}")

    # stable sort groups events by aggregate while preserving per-aggregate time order;
    # sorted_by_aggregate is a no-op on the hot path (replay_columnar slices an
    # already-sorted log)
    srt = colev.sorted_by_aggregate()
    sorted_agg, src_tids, src_cols = srt.agg_idx, srt.type_ids, srt.cols
    starts = np.zeros(b + 1, dtype=np.int64)
    np.cumsum(lengths, out=starts[1:])
    slot = np.arange(n, dtype=np.int64) - starts[sorted_agg]

    type_ids = np.full((b, t), PAD_TYPE_ID, dtype=np.int32)
    type_ids[sorted_agg, slot] = src_tids
    cols = {}
    for name, col in src_cols.items():
        buf = np.zeros((b, t), dtype=col.dtype)
        buf[sorted_agg, slot] = col
        cols[name] = buf
    return EncodedEvents(type_ids=type_ids, cols=cols, lengths=lengths,
                         derived_cols=dict(colev.derived_cols))


def encode_events_columnar(registry: SchemaRegistry,
                           event_logs: Sequence[Sequence[Any]]) -> ColumnarEvents:
    """Flatten object logs into the columnar layout. Groups the per-event Python work
    by event type so each field extracts in one comprehension per (type, field) rather
    than a nested per-event/per-field loop."""
    union = registry.union_columns()
    flat: list[Any] = []
    agg_idx_parts: list[np.ndarray] = []
    for i, log in enumerate(event_logs):
        flat.extend(log)
        agg_idx_parts.append(np.full(len(log), i, dtype=np.int32))
    n = len(flat)
    agg_idx = (np.concatenate(agg_idx_parts) if agg_idx_parts
               else np.zeros(0, dtype=np.int32))

    type_ids = np.empty(n, dtype=np.int32)
    by_type: dict[type, list[int]] = {}
    for k, ev in enumerate(flat):
        by_type.setdefault(type(ev), []).append(k)
    cols = {f.name: np.zeros(n, dtype=f.dtype) for f in union}
    for cls, idxs in by_type.items():
        schema = registry.schema_for_cls(cls)
        ii = np.asarray(idxs, dtype=np.int64)
        type_ids[ii] = schema.type_id
        getter = schema.getter
        for f in schema.fields:
            name = f.name
            cols[name][ii] = [getter(flat[k], name) for k in idxs]
    return ColumnarEvents(num_aggregates=len(event_logs), agg_idx=agg_idx,
                          type_ids=type_ids, cols=cols)


def encode_events(registry: SchemaRegistry, event_logs: Sequence[Sequence[Any]],
                  pad_to: int | None = None) -> EncodedEvents:
    """Encode ragged per-aggregate event lists into a dense tagged-union batch."""
    colev = encode_events_columnar(registry, event_logs)
    enc = columnar_to_batch(colev, pad_to=pad_to)
    return enc


def decode_events(registry: SchemaRegistry, enc: EncodedEvents) -> list[list[Any]]:
    """Inverse of :func:`encode_events` — for golden round-trip tests."""
    out: list[list[Any]] = []
    for i in range(enc.batch_size):
        log: list[Any] = []
        for j in range(int(enc.lengths[i])):
            tid = int(enc.type_ids[i, j])
            schema = registry.schema_for_id(tid)
            kwargs = {}
            for f in schema.fields:
                v = enc.cols[f.name][i, j]
                if f.dtype.kind == "b":
                    kwargs[f.name] = bool(v)
                elif f.dtype.kind in "iu":
                    kwargs[f.name] = int(v)
                else:
                    kwargs[f.name] = float(v)
            log.append(_construct(schema.cls, kwargs))
        out.append(log)
    return out


_EXCLUDED_DEFAULTS = {str: "", int: 0, float: 0.0, bool: False}


def _construct(cls: type, kwargs: dict[str, Any]) -> Any:
    """Build a dataclass instance, filling fields excluded from the tensor schema
    (e.g. aggregate-id strings) with neutral defaults."""
    import dataclasses

    for f in dataclasses.fields(cls):
        if f.name in kwargs:
            continue
        if f.default is not dataclasses.MISSING or f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
            continue
        ann = f.type if isinstance(f.type, type) else {"str": str, "int": int,
                                                       "float": float, "bool": bool}.get(str(f.type))
        kwargs[f.name] = _EXCLUDED_DEFAULTS.get(ann, None)
    return cls(**kwargs)


def encode_states(schema: StateSchema, states: Sequence[Any]) -> dict[str, np.ndarray]:
    """Batch scalar states into the dict-of-arrays carry pytree ``{name: [B]}``."""
    out: dict[str, np.ndarray] = {}
    for f in schema.fields:
        out[f.name] = np.asarray([getattr(s, f.name) for s in states], dtype=f.dtype)
    return out


def decode_states(schema: StateSchema, tree: Mapping[str, np.ndarray]) -> list[Any]:
    """Inverse of :func:`encode_states`."""
    arrays = {f.name: np.asarray(tree[f.name]) for f in schema.fields}
    b = len(next(iter(arrays.values()))) if arrays else 0
    return [schema.from_record({n: a[i] for n, a in arrays.items()}) for i in range(b)]


def bucket_lengths(lengths: Sequence[int], buckets: Sequence[int]) -> dict[int, list[int]]:
    """Group aggregate indices into padded-length buckets (ragged batching).

    Returns {bucket_cap: [indices]} where each log fits its bucket. Logs longer than the
    largest bucket go into a final bucket rounded up to the next multiple of it.
    """
    if not buckets:
        raise ValueError("need at least one bucket size")
    caps = sorted(buckets)
    groups: dict[int, list[int]] = {}
    for idx, ln in enumerate(lengths):
        cap = next((c for c in caps if ln <= c), None)
        if cap is None:
            biggest = caps[-1]
            cap = ((ln + biggest - 1) // biggest) * biggest
        groups.setdefault(cap, []).append(idx)
    return groups
