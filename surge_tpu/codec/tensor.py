"""Struct-of-arrays encoding of ragged per-aggregate event logs.

Layout (``EncodedEvents``), chosen for the TPU scan (SURVEY.md §7 "Event→tensor codec"):

- ``type_ids``: int32 ``[B, T]`` — tagged-union discriminant; ``PAD_TYPE_ID`` (-1) marks
  padding past each aggregate's log length.
- ``cols``: dict of ``[B, T]`` arrays, one per union column (see
  ``SchemaRegistry.union_columns``). Fields an event type lacks are zero-filled.
- ``lengths``: int32 ``[B]`` — true log lengths (mask = position < length).

B is the aggregate batch dimension (vmap/shard axis), T the time dimension (lax.scan
axis). Encoding is pure NumPy on the host; the replay engine moves arrays to device and
transposes to time-major itself.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

import numpy as np

from surge_tpu.codec.schema import SchemaRegistry, StateSchema

PAD_TYPE_ID = -1


@dataclass
class EncodedEvents:
    type_ids: np.ndarray  # [B, T] int32
    cols: dict[str, np.ndarray]  # each [B, T]
    lengths: np.ndarray  # [B] int32

    @property
    def batch_size(self) -> int:
        return int(self.type_ids.shape[0])

    @property
    def max_len(self) -> int:
        return int(self.type_ids.shape[1])

    def mask(self) -> np.ndarray:
        """bool [B, T]: True where a real event exists."""
        return self.type_ids != PAD_TYPE_ID

    def nbytes(self) -> int:
        return self.type_ids.nbytes + self.lengths.nbytes + sum(c.nbytes for c in self.cols.values())


def encode_events(registry: SchemaRegistry, event_logs: Sequence[Sequence[Any]],
                  pad_to: int | None = None) -> EncodedEvents:
    """Encode ragged per-aggregate event lists into a dense tagged-union batch."""
    b = len(event_logs)
    lengths = np.asarray([len(log) for log in event_logs], dtype=np.int32)
    t = int(pad_to) if pad_to is not None else int(lengths.max(initial=0))
    if lengths.size and lengths.max(initial=0) > t:
        raise ValueError(f"pad_to={t} < longest log {int(lengths.max())}")

    type_ids = np.full((b, t), PAD_TYPE_ID, dtype=np.int32)
    union = registry.union_columns()
    cols = {f.name: np.zeros((b, t), dtype=f.dtype) for f in union}

    for i, log in enumerate(event_logs):
        for j, event in enumerate(log):
            schema = registry.schema_for(event)
            type_ids[i, j] = schema.type_id
            for f in schema.fields:
                cols[f.name][i, j] = schema.getter(event, f.name)
    return EncodedEvents(type_ids=type_ids, cols=cols, lengths=lengths)


def decode_events(registry: SchemaRegistry, enc: EncodedEvents) -> list[list[Any]]:
    """Inverse of :func:`encode_events` — for golden round-trip tests."""
    out: list[list[Any]] = []
    for i in range(enc.batch_size):
        log: list[Any] = []
        for j in range(int(enc.lengths[i])):
            tid = int(enc.type_ids[i, j])
            schema = registry.schema_for_id(tid)
            kwargs = {}
            for f in schema.fields:
                v = enc.cols[f.name][i, j]
                if f.dtype.kind == "b":
                    kwargs[f.name] = bool(v)
                elif f.dtype.kind in "iu":
                    kwargs[f.name] = int(v)
                else:
                    kwargs[f.name] = float(v)
            log.append(_construct(schema.cls, kwargs))
        out.append(log)
    return out


_EXCLUDED_DEFAULTS = {str: "", int: 0, float: 0.0, bool: False}


def _construct(cls: type, kwargs: dict[str, Any]) -> Any:
    """Build a dataclass instance, filling fields excluded from the tensor schema
    (e.g. aggregate-id strings) with neutral defaults."""
    import dataclasses

    for f in dataclasses.fields(cls):
        if f.name in kwargs:
            continue
        if f.default is not dataclasses.MISSING or f.default_factory is not dataclasses.MISSING:  # type: ignore[misc]
            continue
        ann = f.type if isinstance(f.type, type) else {"str": str, "int": int,
                                                       "float": float, "bool": bool}.get(str(f.type))
        kwargs[f.name] = _EXCLUDED_DEFAULTS.get(ann, None)
    return cls(**kwargs)


def encode_states(schema: StateSchema, states: Sequence[Any]) -> dict[str, np.ndarray]:
    """Batch scalar states into the dict-of-arrays carry pytree ``{name: [B]}``."""
    out: dict[str, np.ndarray] = {}
    for f in schema.fields:
        out[f.name] = np.asarray([getattr(s, f.name) for s in states], dtype=f.dtype)
    return out


def decode_states(schema: StateSchema, tree: Mapping[str, np.ndarray]) -> list[Any]:
    """Inverse of :func:`encode_states`."""
    arrays = {f.name: np.asarray(tree[f.name]) for f in schema.fields}
    b = len(next(iter(arrays.values()))) if arrays else 0
    return [schema.from_record({n: a[i] for n, a in arrays.items()}) for i in range(b)]


def bucket_lengths(lengths: Sequence[int], buckets: Sequence[int]) -> dict[int, list[int]]:
    """Group aggregate indices into padded-length buckets (ragged batching).

    Returns {bucket_cap: [indices]} where each log fits its bucket. Logs longer than the
    largest bucket go into a final bucket rounded up to the next multiple of it.
    """
    if not buckets:
        raise ValueError("need at least one bucket size")
    caps = sorted(buckets)
    groups: dict[int, list[int]] = {}
    for idx, ln in enumerate(lengths):
        cap = next((c for c in caps if ln <= c), None)
        if cap is None:
            biggest = caps[-1]
            cap = ((ln + biggest - 1) // biggest) * biggest
        groups.setdefault(cap, []).append(idx)
    return groups
