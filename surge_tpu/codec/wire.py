"""Bit-packed host→device wire format for event windows.

Host→device transfer is the replay engine's bottleneck (SURVEY.md §7 hard-part 2: a
100M-event log at 4 int32 columns is 1.6 GB on the wire; the fold itself is a few int
ops per event). This module shrinks the wire to the information actually present:

- The **type discriminant** and every union column with a declared ``FieldSpec.bits``
  width are packed into one little-endian word of ``ceil(total_bits/8)`` bytes per
  event (``packed``: uint8 ``[T, B, nbytes]``). The Counter fixture's events — type
  (3 bits incl. padding sentinel) + increment_by (2) + decrement_by (2) — fit in
  **one byte per event**, 16× less wire than the naive int32 columns.
- Columns without ``bits`` ride as full-width **side** arrays ``[T, B]`` (floats,
  wide ints).
- **Derived columns** never cross the wire at all: a data producer that knows a column
  is positional (``derived_cols={"sequence_number": "ordinal"}`` on
  ``ColumnarEvents``/``EncodedEvents``) lets the device recompute it as
  ``base + time_index + 1``. Event-sourced sequence numbers are ordinal by
  construction in the steady-state log (seq == offset within the aggregate's
  stream), so bulk replay of framework-written logs always qualifies; object-encoded
  test logs keep the explicit column.

Packing is pure vectorized NumPy; unpacking is jitted JAX that the fold program fuses
with the scan, so decode costs no extra HBM round trip.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Mapping

import numpy as np

from surge_tpu.codec.schema import FieldSpec, SchemaRegistry

#: derivation kinds a producer may declare for a column
DERIVE_ORDINAL = "ordinal"

_MAX_PACKED_BITS = 32  # one uint32 word per event; wider layouts spill to side columns


@dataclass(frozen=True)
class _PackedField:
    name: str
    dtype: np.dtype
    bits: int
    shift: int

    @property
    def mask(self) -> int:
        return (1 << self.bits) - 1


class WireFormat:
    """Pack/unpack schedule for one (registry, derived-columns) pair."""

    def __init__(self, registry: SchemaRegistry,
                 derived: Mapping[str, str] | None = None) -> None:
        self.registry = registry
        self.derived = dict(derived or {})
        for name, kind in self.derived.items():
            if kind != DERIVE_ORDINAL:
                raise ValueError(f"unknown derivation {kind!r} for column {name!r}")

        num_types = registry.num_event_types
        self.num_types = num_types
        self.type_bits = max(int(num_types).bit_length(), 1)  # +1 value: pad sentinel
        self.pad_code = num_types

        shift = self.type_bits
        packed: list[_PackedField] = []
        side: list[FieldSpec] = []
        self.derived_fields: list[FieldSpec] = []
        for f in registry.union_columns():
            if f.name in self.derived:
                self.derived_fields.append(f)
            elif f.bits is not None and shift + f.bits <= _MAX_PACKED_BITS:
                packed.append(_PackedField(f.name, f.dtype, f.bits, shift))
                shift += f.bits
            else:
                side.append(f)
        self.packed_fields = tuple(packed)
        self.side_fields = tuple(side)
        self.total_bits = shift
        self.nbytes = (shift + 7) // 8
        # the byte pattern a padding slot must decode to: pad_code in the type bits,
        # zeros elsewhere
        self.pad_bytes = tuple((self.pad_code >> (8 * k)) & 0xFF
                               for k in range(self.nbytes))

    def wire_bytes_per_event(self) -> int:
        """Transfer cost per event slot (packed word + side columns)."""
        return self.nbytes + sum(f.dtype.itemsize for f in self.side_fields)

    def layout_fingerprint(self) -> dict:
        """A JSON-round-trippable description of the exact bit/byte layout.

        Persisted next to packed corpora (ResidentWire meta) so a consuming
        engine whose schema evolved — field widths, order, type count — is
        refused instead of decoding misaligned bits into silently-wrong
        states. Two schemas that pack to the same byte count but different bit
        positions produce different fingerprints."""
        return {
            "num_types": self.num_types,
            "type_bits": self.type_bits,
            "nbytes": self.nbytes,
            "packed": [[pf.name, str(np.dtype(pf.dtype)), pf.bits, pf.shift]
                       for pf in self.packed_fields],
            "side": [[f.name, str(np.dtype(f.dtype))]
                     for f in self.side_fields],
            "derived": sorted([k, v] for k, v in self.derived.items()),
        }

    # -- host side ----------------------------------------------------------------------

    def pack_window(self, type_ids: np.ndarray, cols: Mapping[str, np.ndarray],
                    start: int, stop: int, chunk: int, bs: int
                    ) -> tuple[np.ndarray, dict[str, np.ndarray]]:
        """Pack the time window ``[:, start:stop)`` of a batch-major ``[b, T]`` layout
        into time-major device-ready buffers padded to ``[chunk, bs]``.

        Returns ``(packed uint8 [chunk, bs, nbytes], side {name: [chunk, bs]})``.
        Fresh buffers every call (donation-safe). Padding slots decode to the pad
        sentinel. Raises if a packed field's value overflows its declared bits.
        """
        b = type_ids.shape[0]
        width = stop - start
        word = self._pack_words(type_ids[:, start:stop],
                                {pf.name: cols[pf.name][:, start:stop]
                                 for pf in self.packed_fields})

        packed = np.empty((chunk, bs, self.nbytes), dtype=np.uint8)
        for k in range(self.nbytes):
            packed[..., k] = self.pad_bytes[k]
            packed[:width, :b, k] = ((word >> np.asarray(8 * k, dtype=word.dtype))
                                     & np.asarray(0xFF, dtype=word.dtype)).T

        side: dict[str, np.ndarray] = {}
        for f in self.side_fields:
            buf = np.zeros((chunk, bs), dtype=f.dtype)
            buf[:width, :b] = cols[f.name][:, start:stop].T
            side[f.name] = buf
        return packed, side

    def _pack_words(self, type_ids: np.ndarray,
                    cols: Mapping[str, np.ndarray]) -> np.ndarray:
        """The shared word-build: out-of-range ids — padding (-1) or corrupt
        positive values — pack as the pad sentinel so they carry state through
        (the same contract make_step_fn keeps for the unpacked path); a corrupt
        id must never spill into field bits. Dtype-preserving range checks
        catch negatives and any value past each declared width."""
        # narrowest word dtype that holds every packed bit: at bench scale the
        # build streams N×4-byte intermediates per field, so a 1-byte wire
        # (counter) building in uint8 moves a quarter of the memory
        wdtype = (np.uint8 if self.nbytes == 1
                  else np.uint16 if self.nbytes == 2 else np.uint32)
        tid = np.asarray(type_ids)
        word = np.where((tid < 0) | (tid >= self.num_types),
                        self.pad_code, tid).astype(wdtype)
        for pf in self.packed_fields:
            col = np.asarray(cols[pf.name])
            if col.size and ((col < 0) | (col > pf.mask)).any():
                raise ValueError(
                    f"column {pf.name!r} overflows its declared {pf.bits}-bit "
                    f"wire width (max value {int(col.max())}, "
                    f"min {int(col.min())})")
            word |= (col.astype(wdtype)
                     << np.asarray(pf.shift, dtype=wdtype))
        return word

    def pack_flat(self, type_ids: np.ndarray, cols: Mapping[str, np.ndarray]
                  ) -> tuple[np.ndarray, dict[str, np.ndarray]]:
        """Pack a FLAT event stream ``[N]`` into ``(packed uint8 [N, nbytes],
        side {name: [N]})`` — the resident-corpus wire form: exactly
        ``wire_bytes_per_event()`` per real event, no window padding at all.
        The device slices per-aggregate slabs from it (see
        :meth:`decode_words`)."""
        word = self._pack_words(type_ids,
                                {pf.name: cols[pf.name]
                                 for pf in self.packed_fields})
        n = word.shape[0]
        packed = np.empty((n, self.nbytes), dtype=np.uint8)
        for k in range(self.nbytes):
            packed[:, k] = ((word >> np.asarray(8 * k, dtype=word.dtype))
                            & np.asarray(0xFF, dtype=word.dtype))
        side = {f.name: np.ascontiguousarray(cols[f.name], dtype=f.dtype)
                for f in self.side_fields}
        return packed, side

    # -- device side ----------------------------------------------------------------------

    def expand_flat(self, packed: Any) -> Any:
        """One-time on-device expansion of the flat packed bytes to a u32 word
        array ``[N]`` (gather-friendly lanes; HBM-resident, never transferred)."""
        import jax.numpy as jnp

        word = packed[:, 0].astype(jnp.uint32)
        for k in range(1, self.nbytes):
            word = word | (packed[:, k].astype(jnp.uint32) << np.uint32(8 * k))
        return word

    def decode_words(self, word: Any, side_row: Mapping[str, Any], valid: Any,
                     ord_base: Any, t: Any) -> dict[str, Any]:
        """JAX-traceable decode of one scan step's word row ``[B]`` (extracted
        from a resident flat corpus by contiguous per-lane slabs): slots with
        ``valid`` false decode to the pad sentinel, and the derived ordinal is
        ``ord_base[b] + t + 1``."""
        import jax.numpy as jnp

        tid = (word & np.uint32((1 << self.type_bits) - 1)).astype(jnp.int32)
        tid = jnp.where(tid >= self.num_types, jnp.int32(-1), tid)
        events: dict[str, Any] = {
            "type_id": jnp.where(valid, tid, jnp.int32(-1))}
        for pf in self.packed_fields:
            raw = (word >> np.uint32(pf.shift)) & np.uint32(pf.mask)
            events[pf.name] = raw.astype(pf.dtype)
        for f in self.side_fields:
            events[f.name] = side_row[f.name]
        for f in self.derived_fields:
            events[f.name] = (ord_base.astype(jnp.int32) + t + 1).astype(f.dtype)
        return events

    def decode(self, packed: Any, side: Mapping[str, Any], ord_base: Any
               ) -> dict[str, Any]:
        """JAX-traceable unpack: ``[chunk, B, nbytes]`` uint8 (+side columns, +ordinal
        base ``[B]``) → the events dict the fold scan consumes, with ``type_id`` as
        int32 (padding → -1) and each field at its schema dtype.

        ``ord_base[b] + t + 1`` is the derived ordinal of the event at time row ``t``
        (0 for fresh replays; the already-folded event count when resuming).
        """
        import jax.numpy as jnp

        chunk = packed.shape[0]
        word = packed[..., 0].astype(jnp.uint32)
        for k in range(1, self.nbytes):
            word = word | (packed[..., k].astype(jnp.uint32) << np.uint32(8 * k))

        tid = (word & np.uint32((1 << self.type_bits) - 1)).astype(jnp.int32)
        events: dict[str, Any] = {
            "type_id": jnp.where(tid >= self.num_types, jnp.int32(-1), tid)}
        for pf in self.packed_fields:
            raw = (word >> np.uint32(pf.shift)) & np.uint32(pf.mask)
            events[pf.name] = raw.astype(pf.dtype)
        for f in self.side_fields:
            events[f.name] = side[f.name]
        if self.derived_fields:
            t_idx = jnp.arange(chunk, dtype=jnp.int32)[:, None]
            for f in self.derived_fields:
                ordinal = ord_base[None, :].astype(jnp.int32) + t_idx + 1
                events[f.name] = ordinal.astype(f.dtype)
        return events
