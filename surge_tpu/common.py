"""Shared runtime primitives: lifecycle contract, ring buffer, task supervision.

Equivalents of the reference's ``surge.core.Controllable``/``Ack``
(modules/common/src/main/scala/surge/core/Controllable.scala:7-34), ``CircularBuffer``
(surge/internal/utils/CircularBuffer.scala), and the Akka actor-lifecycle plumbing
(``ActorLifecycleManagerActor``) — re-expressed for asyncio tasks instead of actors.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Callable, Coroutine, Generic, List, Optional, TypeVar

logger = logging.getLogger("surge_tpu")

T = TypeVar("T")


class Ack:
    """Positive acknowledgement of a lifecycle op (surge.core.Ack)."""

    def __repr__(self) -> str:  # pragma: no cover
        return "Ack()"


class Controllable:
    """Lifecycle contract: start/stop/restart/shutdown (Controllable.scala:7-34).

    Components (store indexer, publishers, router, pipeline) subclass this; the health
    supervisor restarts registered Controllables when fatal signal patterns match
    (HealthSupervisorActor.scala:63-111 analog).
    """

    async def start(self) -> Ack:
        raise NotImplementedError

    async def stop(self) -> Ack:
        raise NotImplementedError

    async def restart(self) -> Ack:
        await self.stop()
        return await self.start()

    async def shutdown(self) -> Ack:
        """Terminal stop (no restart expected)."""
        return await self.stop()


class DecodedState:
    """An already-deserialized aggregate state handed back by a state fetch.

    The resident state plane (surge_tpu.replay.resident_state) materializes
    domain states from device tensor rows, so routing them through the
    byte-oriented fetch contract would serialize + immediately re-deserialize
    every hit. A fetch returning ``DecodedState(state)`` tells the entity to
    adopt ``state`` directly. Defined here (jax-free) so the core engine never
    imports the replay stack just to recognize the marker."""

    __slots__ = ("state",)

    def __init__(self, state: Any) -> None:
        self.state = state


class CircularBuffer(Generic[T]):
    """Fixed-capacity ring (CircularBuffer.scala analog; health bus keeps the last N
    signals in one of these — HealthSignalBus.scala:177)."""

    def __init__(self, capacity: int) -> None:
        self._capacity = max(int(capacity), 1)
        self._items: List[T] = []
        self._next = 0

    def push(self, item: T) -> None:
        if len(self._items) < self._capacity:
            self._items.append(item)
        else:
            self._items[self._next] = item
        self._next = (self._next + 1) % self._capacity

    def to_list(self) -> List[T]:
        """Oldest→newest."""
        if len(self._items) < self._capacity:
            return list(self._items)
        return self._items[self._next:] + self._items[: self._next]

    def __len__(self) -> int:
        return len(self._items)


class BackgroundTask:
    """A supervised asyncio loop task with clean cancel-on-stop semantics."""

    def __init__(self, coro_factory: Callable[[], Coroutine[Any, Any, None]],
                 name: str) -> None:
        self._factory = coro_factory
        self._name = name
        self._task: Optional[asyncio.Task] = None

    def start(self) -> None:
        if self._task is None or self._task.done():
            self._task = asyncio.ensure_future(self._factory())
            self._task.set_name(self._name)

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is None or task.done():
            return
        task.cancel()
        if task is asyncio.current_task():
            return  # self-stop: the cancellation lands at our next await point
        # A single cancel() is not enough on Python 3.10: asyncio.wait_for can
        # swallow a cancellation that races a timeout or a completing inner
        # future (bpo-37658 family), so a loop built on wait_for keeps running
        # and a bare `await task` hangs forever — the tier-1 cluster-test hang
        # (stop chains stuck on the indexer during set_partitions). Re-issue
        # the cancel on a short deadline until the task actually ends.
        for attempt in range(120):
            try:
                await asyncio.wait_for(asyncio.shield(task), timeout=0.25)
                return
            except asyncio.TimeoutError:
                task.cancel()
                if attempt == 19:
                    logger.warning("background task %s ignored cancellation "
                                   "for 5s; re-cancelling", self._name)
            except asyncio.CancelledError:
                if task.done():
                    return  # the task ended cancelled — the normal stop path
                raise  # stop() itself was cancelled
            except Exception:  # noqa: BLE001 — stop is best-effort
                return
        logger.error("background task %s failed to stop after repeated "
                     "cancellation; abandoning the await", self._name)

    @property
    def running(self) -> bool:
        return self._task is not None and not self._task.done()


async def cancel_safe_wait_for(awaitable, timeout: float):
    """Drop-in ``asyncio.wait_for`` without the py3.10 cancellation swallow.

    ``asyncio.wait_for`` can swallow an outer cancellation that races a
    timeout or a completing inner future (bpo-37658 family): it catches the
    ``CancelledError``, returns the inner result, and the caller's loop keeps
    running after ``task.cancel()`` — the tier-1 cluster-test hang. Built on
    ``asyncio.wait``, which never catches cancellation: a racing cancel stays
    pending on the task (``_must_cancel``) and fires at the caller's next
    await point instead of vanishing.

    Same contract as ``wait_for``: returns the result, raises
    ``asyncio.TimeoutError`` on timeout (the awaitable is cancelled AND
    awaited first, exactly like ``wait_for``'s ``_cancel_and_wait`` — that
    extra suspension point matters: a caller cancelled while parked here must
    die at the timeout boundary, not run one more loop body), propagates the
    awaitable's exception. If the awaitable completes in the cancel window —
    beating the timeout's cancel — its real result/exception is returned/
    raised rather than masked as TimeoutError (and rather than rotting as an
    unretrieved task exception).
    """
    task = asyncio.ensure_future(awaitable)
    try:
        done, _ = await asyncio.wait((task,), timeout=timeout)
    except BaseException:
        task.cancel()
        try:
            # bpo-32751 parity: the inner task must not outlive wait_for —
            # its cleanup (e.g. a publish lane's finally) finishes before the
            # caller's CancelledError propagates
            await asyncio.wait((task,))
        finally:
            if task.done() and not task.cancelled():
                task.exception()  # retrieve: don't rot as 'never retrieved'
        raise
    if task in done:
        return task.result()
    task.cancel()
    try:
        await asyncio.wait((task,))  # cancellation of the CALLER lands here too
    except BaseException:
        if task.done() and not task.cancelled():
            task.exception()  # retrieve before the caller's cancel wins
        raise
    if not task.cancelled():
        return task.result()  # completion (or a real failure) beat the cancel
    raise asyncio.TimeoutError


async def wait_future(fut: "asyncio.Future", timeout: float,
                      owned: bool = True):
    """Await a BARE future with a timeout — the per-command de-asyncio'd
    twin of :func:`cancel_safe_wait_for` for plain futures. One
    ``call_later`` handle instead of a wrapper task + ``asyncio.wait``'s
    waiter/callback machinery; at engine throughput that difference is paid
    once per command (BENCH_NOTES round 9).

    ``owned=True`` (an exclusively-held future, e.g. an ask reply): the
    timeout CANCELS the future — exactly ``wait_for``'s contract, so a
    producer resolving late finds it cancelled and no-ops. An OUTER task
    cancellation also lands on the future (the task cancels what it awaits),
    and is re-raised — never swallowed, never misread as a timeout.

    ``owned=False`` (a SHARED future, e.g. the publisher direct lane's
    per-batch ack): the timeout must not cancel what other waiters ride, so
    this waiter parks on its own future instead and leaves the shared one
    untouched on timeout AND on outer cancellation.
    """
    if fut.done():
        if not owned and fut.cancelled():
            # same contract as the shared branch below: a co-holder's
            # cancellation surfaces retryable, never CancelledError
            raise RuntimeError("shared future was cancelled by another holder")
        return fut.result()
    loop = asyncio.get_running_loop()
    if owned:
        timed_out = False

        def _on_timeout() -> None:
            nonlocal timed_out
            timed_out = True
            fut.cancel()

        handle = loop.call_later(timeout, _on_timeout)
        try:
            return await fut
        except asyncio.CancelledError:
            if timed_out and fut.cancelled():
                raise asyncio.TimeoutError from None
            raise
        finally:
            handle.cancel()
    waiter: "asyncio.Future" = loop.create_future()

    def _done(f: "asyncio.Future") -> None:
        resolve_future(waiter, f)

    fut.add_done_callback(_done)
    handle = loop.call_later(timeout, resolve_future, waiter, None)
    try:
        inner = await waiter
    finally:
        handle.cancel()
        fut.remove_done_callback(_done)
    if inner is None:
        raise asyncio.TimeoutError
    if inner.cancelled():
        # ANOTHER holder cancelled the shared future. This waiter did not:
        # surface a plain retryable failure, not CancelledError — a
        # BaseException here would blow through the caller's retry ladder
        # and kill a command whose write may well still commit.
        raise RuntimeError("shared future was cancelled by another holder")
    return inner.result()


def spawn_reaped(registry: set, coro: Coroutine[Any, Any, Any],
                 what: str) -> "asyncio.Task":
    """Spawn a fire-and-forget coroutine WITHOUT orphaning it: the task is
    retained in ``registry`` (so it cannot be garbage-collected mid-flight),
    discarded when done, and a non-cancellation failure is logged instead of
    rotting until interpreter exit. The house pattern behind the orphan-task
    lint rule — use this (or BackgroundTask for loops) wherever the result
    genuinely has no awaiter."""
    task = asyncio.ensure_future(coro)
    registry.add(task)

    def _reap(t: "asyncio.Task") -> None:
        registry.discard(t)
        if not t.cancelled() and t.exception() is not None:
            logger.error("%s failed", what, exc_info=t.exception())

    task.add_done_callback(_reap)
    return task


def resolve_future(fut: "asyncio.Future[T]", value: T) -> None:
    if not fut.done():
        fut.set_result(value)


def fail_future(fut: asyncio.Future, exc: BaseException) -> None:
    if not fut.done():
        fut.set_exception(exc)
