"""Config system — typed accessors over layered key/value config with env overrides.

Equivalent of the reference's Typesafe-Config (HOCON) ``reference.conf`` stack
(modules/common/src/main/resources/reference.conf, modules/command-engine/core/src/main/
resources/reference.conf) including the env-var-override-on-every-key pattern and the
typed accessor objects (surge/internal/config/{TimeoutConfig,RetryConfig,BackoffConfig}.scala).

Keys are dotted strings (``surge.producer.flush-interval-ms``). Resolution order:
explicit overrides > environment (``SURGE_PRODUCER_FLUSH_INTERVAL_MS``) > defaults.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Mapping


def _env_key(key: str) -> str:
    return key.upper().replace(".", "_").replace("-", "_")


#: Defaults mirroring the reference's reference.conf files (values in ms unless noted).
#: Citations: command-engine/core reference.conf:20-30 (flush interval, txn timeout,
#: ktable lag check), common reference.conf:15-21 (streams commit interval), :133-142
#: (aggregate init retries), :155-165 (ask timeout / passivation), :198-199 (restore
#: max poll records), :228-260 (health windows).
DEFAULTS: dict[str, Any] = {
    # --- log / producer (reference: surge.kafka.publisher.*) ---
    # group-commit flush triggers (the Kafka producer linger.ms /
    # batch.size analog): a batch commits when the FIRST pending publish has
    # lingered this long OR the batch hits max-records/max-bytes, whichever
    # comes first. An idle engine therefore commits a lone command in
    # ~linger time; a loaded engine fills batches.
    "surge.producer.linger-ms": 2,
    "surge.producer.batch-max-records": 512,
    "surge.producer.batch-max-bytes": 4 << 20,
    # bounded pipelining (max.in.flight.requests.per.connection analog):
    # how many publish transactions one partition lane may have in flight
    # concurrently. >1 requires a transport with pipelined commits (the gRPC
    # log client); exactly-once rests on the broker's per-producer txn_seq
    # dedup + in-order apply gate. In-process logs fall back to 1 (their
    # commit latency IS the group-commit pacing).
    "surge.producer.max-in-flight": 4,
    # backpressure: publishes past this many queued records await a slot
    # instead of growing the lane queue without bound under overload
    "surge.producer.pending-max-records": 16_384,
    # housekeeping tick: fenced-reinit retries, verbatim-retry pacing and
    # dedup-TTL purges run on this cadence (pre-group-commit it was the
    # fixed flush tick; the flush itself is event-driven now)
    "surge.producer.flush-interval-ms": 50,
    "surge.producer.slow-transaction-warning-ms": 1_000,
    "surge.producer.ktable-check-interval-ms": 500,
    "surge.producer.enable-transactions": True,
    # publish dedup window (the PublishTracker 60s TTL, KafkaProducerActorImpl.scala:580-608)
    "surge.producer.publish-dedup-ttl-ms": 60_000,
    # verbatim retries of an unknown-outcome batch before its waiters fail
    # over to the entity retry ladder
    "surge.producer.publish-retry-max": 8,
    # --- state store / ktable (reference: surge.kafka-streams.*) ---
    "surge.state-store.commit-interval-ms": 3_000,
    "surge.state-store.restore-max-poll-records": 500,
    "surge.state-store.wipe-state-on-start": False,
    "surge.state-store.backend": "memory",  # memory | native | rocks-like file store
    # warm standby copies of each partition's materialized state on other nodes
    # (Kafka Streams num.standby.replicas, common reference.conf:24-25): each
    # node also tails the partitions it is ring-standby for, so a rebalance
    # promotion needs no state re-read
    "surge.state-store.num-standby-replicas": 0,
    # --- aggregate actor (reference: surge.state-store-actor.*) ---
    "surge.aggregate.ask-timeout-ms": 30_000,
    "surge.aggregate.idle-passivation-ms": 30_000,
    "surge.aggregate.init-retry-interval-ms": 500,
    "surge.aggregate.init-fetch-retry-ms": 2_000,
    "surge.aggregate.init-max-attempts": 10,
    "surge.aggregate.publish-max-retries": 3,
    "surge.aggregate.publish-timeout-ms": 30_000,
    "surge.aggregate.passivation-buffer-limit": 1000,
    # --- serialization (core reference.conf:73-76) ---
    "surge.serialization.thread-pool-size": 32,
    # command-path fast path: event batches at most this long serialize
    # INLINE on the event loop instead of paying the thread-pool hop (~80us
    # per command) — big payloads still offload. 0 = always off-thread.
    "surge.serialization.inline-max-events": 4,
    # --- metrics ---
    # capture OpenMetrics exemplars (trace id per histogram bucket) on the
    # ENGINE registry; broker registries are always exemplar-on
    "surge.metrics.exemplars": False,
    # --- tracing: tail sampling + kept-trace rings (surge_tpu/tracing/tail) ---
    # buffer head-sampled spans per trace and KEEP a completed trace iff it
    # erred, breached tail.latency-ms, or landed in an SLO breach window —
    # under a bounded keep budget. Only matters when a tracer is wired
    # (tracer=None keeps every hop at zero cost as before).
    "surge.trace.tail.enabled": True,
    # a trace whose slowest span ran at least this is kept (the latency
    # breach criterion of the tail decision)
    "surge.trace.tail.latency-ms": 250,
    # keep budget: at most this many kept traces per budget window; eligible
    # traces past it are dropped and counted (surge.trace.dropped)
    "surge.trace.tail.keep-budget": 64,
    "surge.trace.tail.budget-window-ms": 10_000,
    # bound on spans buffered for in-flight traces; oldest traces evict past
    # it (leaked spans must not grow the buffer without bound)
    "surge.trace.tail.max-buffer-spans": 4096,
    # how long after an SLO breach every completing trace is kept (the
    # breach-adjacent anatomy evidence window)
    "surge.trace.tail.breach-window-ms": 30_000,
    # kept traces retained per engine/broker ring (DumpTraces RPC source)
    "surge.trace.ring-capacity": 256,
    # --- fleet telemetry plane (observability/federation.py + slo.py) ---
    # per-target fetch timeout of one federation pass (HTTP scrape or
    # GetMetricsText RPC); a slower target answers up{instance}=0 and keeps
    # serving its last payload with a staleness stamp
    "surge.fleet.scrape-timeout-ms": 2_000,
    # multiwindow burn-rate alerting (Google-SRE style): a breach fires only
    # when BOTH the fast and the slow window burn over the threshold.
    # 14.4 = the classic 1h/5m page pair's rate (budget exhausted in ~2 days)
    "surge.slo.fast-window-ms": 300_000,
    "surge.slo.slow-window-ms": 3_600_000,
    "surge.slo.burn-threshold": 14.4,
    # --- replay engine (new: the TPU north star; BASELINE.json replayBackend=tpu) ---
    "surge.replay.backend": "tpu",  # tpu | cpu (scalar fold)
    "surge.replay.restore-on-start": False,  # engine cold start folds the events topic
    "surge.replay.batch-size": 8192,  # aggregates per device step
    "surge.replay.time-chunk": 512,  # events scanned per lax.scan segment
    # tail windows shrink through a power-of-two ladder down to this width instead
    # of padding to a full time-chunk (pad_ratio lever; 0/neg disables the ladder)
    "surge.replay.min-time-window": 8,
    # resident-corpus replay: HBM budget for one dispatch's [batch, width] slab
    # (plus its transpose); bounds the scan width of long-log chunks
    "surge.replay.resident-slab-cap-mb": 512,
    # order aggregates by log length before B-chunking so each chunk's local max
    # length ≈ its members' lengths (columnar replay pad_ratio lever)
    "surge.replay.sort-by-length": True,
    "surge.replay.length-buckets": "64,256,1024,4096",
    "surge.replay.mesh-axes": "data",
    "surge.replay.donate-carry": True,
    # donate the resident plane's slab + ordinals through the refresh
    # scatter programs (ISSUE 18 leg c): the round overwrites the slab
    # in place instead of copying it (the round-10 19 ms vs 49 ms device
    # leg at 1M rows WAS the copy). Kill-switchable like donate-carry:
    # false restores copying dispatches (no read path ever sees a
    # deleted buffer either way — the plane republishes the handle per
    # window and the gather lane retries across a donation race)
    "surge.replay.donate-refresh": True,
    # scan-step dispatch ("switch" = lax.switch over schema branches,
    # "select" = compute-all-and-select) and the tile-loop backend ("auto"
    # picks the scanless assoc tree fold for models shipping AssociativeFold)
    "surge.replay.dispatch": "switch",  # switch | select
    "surge.replay.tile-backend": "auto",  # auto | xla | pallas | assoc
    # resident tile layout: "dense" pre-gathers every tile once per corpus
    # when the buffers fit dense-cap-mb of HBM; "flat" gathers per pass
    "surge.replay.resident-layout": "auto",  # auto | flat | dense
    "surge.replay.dense-cap-mb": 2048,
    # bucket resident-corpus row lengths to powers of two ("pow2") so the
    # jit cache sees few shapes, or keep exact lengths ("exact")
    "surge.replay.resident-len-bucket": "pow2",  # pow2 | exact
    # chunked H2D upload: pieces of this many MB pipeline over high-latency
    # links and reassemble on device (0 = single put; single-device resident
    # path only — the sharded upload already ships per-device pieces)
    "surge.replay.upload-chunk-mb": 0,
    # overlap segment-stream uploads with replay dispatches in N segments
    # (0/1 = plain upload+replay)
    "surge.replay.upload-stream-segments": 0,
    # columnar-segment cold start: keep the whole wire corpus resident on
    # device ("resident") or stream per-window ("streaming"); mesh-sharded
    # restores always stream
    "surge.replay.segment-backend": "resident",  # resident | streaming
    # cache the packed wire tensors alongside the segment for re-replays
    "surge.replay.segment-wire-cache": True,
    # columnar-segment cold start: when set, rebuild_from_events streams this
    # segment (building it once from the topics if absent) instead of folding
    # per-event Python objects
    "surge.replay.segment-path": "",
    # append delta chunks/snapshots for post-build offsets on each segment
    # rebuild, so repeated cold starts never re-crawl the topics
    "surge.replay.segment-auto-extend": True,
    # bounded-memory restore_from_events: topics whose total record count
    # exceeds this never materialize as one dict of per-event Python objects —
    # the tpu backend streams through a throwaway columnar segment (spill
    # files + per-chunk encode), the cpu backend folds in key-hash-range
    # passes (the restore consumer max.poll.records role, common
    # reference.conf:198-199). 0 forces the bounded route (cpu passes are
    # capped at 64, trading per-pass memory, not O(N^2) rescans); negative
    # disables spilling entirely.
    "surge.replay.restore-spill-events": 1_000_000,
    # aggregates per chunk for the throwaway restore segment (peak host
    # memory of the bounded tpu path = one chunk's decoded events)
    "surge.replay.restore-chunk-aggregates": 65536,
    # --- device-resident materialized state plane (replay/resident_state.py) ---
    # keep the KTable-equivalent state RESIDENT on device after the cold-start
    # replay, fold committed batches into it incrementally, and answer
    # getState/projections from batched device gathers (ROADMAP item 2)
    "surge.replay.resident.enabled": False,
    # hot-set bound: aggregates resident in the device slab at once; the
    # overflow spills to a host-side dict at its exact fold point and
    # re-admits on its next event
    "surge.replay.resident.capacity": 65536,
    # staleness bound for plane-served reads: a read falls back to the host
    # KV store when its partition's fold watermark lags the committed log by
    # more than this many records (entity init always demands lag 0)
    "surge.replay.resident.max-lag-records": 4096,
    # refresh loop: records pulled per partition per fold round, and how long
    # an idle round waits on wait_for_append before re-polling
    "surge.replay.resident.refresh-max-poll-records": 4096,
    "surge.replay.resident.refresh-interval-ms": 50,
    # refresh feed fast path (ISSUE 12): decode each round's committed tail
    # with ONE batch deserialize (e.g. JsonEventFormatting.read_events_batch)
    # over the native record-index read views, instead of a json.loads +
    # object build per event. false = the per-event Python feed (the paired
    # bench arm; also the behavior when the model wires no batch decoder)
    "surge.replay.resident.native-feed": True,
    # device observatory (ISSUE 16): refresh rounds retained in the engine's
    # bounded replay ledger ring (per-round padding-waste / stage timings /
    # gather legs, dumped via the DumpReplayLedger admin RPC)
    "surge.replay.resident.ledger-capacity": 512,
    # refresh dispatch shape (ISSUE 18): "bucketed" deals each round's lanes
    # into pow2 length buckets and issues one fused program per OCCUPIED
    # bucket (pay for occupied slots, with the compile-signature set bounded
    # by the layout's bucket table); "dense" restores the single
    # [pow8(lanes), pow2(max_len)] rectangle per window (the round-9
    # ~9x over-dispatch arm, kept as the paired-bench baseline)
    "surge.replay.resident.refresh-dispatch": "bucketed",  # bucketed | dense
    # --- mesh-native resident plane (surge_tpu.replay.plane_mesh) ---
    # how a mesh-backed plane resolves reads/folds against its sharded slab:
    # "local" (default) shards the slab [n_dev, rows] and answers each
    # batched read with device-local gathers + ONE cross-device collective,
    # with refresh rounds dealing lanes to their owning shard (one sharded
    # h2d, zero d2h, 1/n_dev fold work per device); "replicated" keeps the
    # legacy plain-jit programs whose gathers replicate the slab every read
    # (the paired-bench baseline arm and the rollback switch)
    "surge.replay.mesh.gather": "local",  # local | replicated
    # --- incremental materialized views + changefeeds (replay/views.py) ---
    # per-view delta ring depth: how many fold rounds a changefeed resume
    # watermark may lag before SubscribeView answers with a one-shot
    # reconciling snapshot instead of replaying the missed deltas
    "surge.replay.views.changefeed-rounds": 256,
    # group cap of one materialized view (distinct aggregate ids or group-by
    # keys); a view that overflows degrades to an error state rather than
    # growing its slab unbounded
    "surge.replay.views.max-groups": 1_048_576,
    # --- TPU scan engine over columnar segments (surge_tpu.replay.query) ---
    # event-axis pad bucket of one scan dispatch: chunks pad up to
    # power-of-two buckets at least this large so streamed chunks reuse a
    # handful of compiled scan programs
    "surge.query.chunk-events": 65536,
    # shard the scan's event axis over the engine's mesh (one psum/pmin/pmax
    # collective per output column); false scans single-device even when a
    # mesh is present
    "surge.query.mesh": True,
    # row cap of one QueryStates/ScanSegments RPC reply (the full columns are
    # available in-process through SurgeEngine.query)
    "surge.query.max-rows": 10_000,
    # --- state checkpoints (surge_tpu.store.checkpoint; compaction.md) ---
    # directory for atomic checkpoint files ("" disables the writer); the
    # incremental writer materializes on interval + min-events cadence and
    # retains the newest `keep` checkpoints
    "surge.store.checkpoint.path": "",
    "surge.store.checkpoint.interval-ms": 30_000,
    "surge.store.checkpoint.min-events": 1,
    "surge.store.checkpoint.keep": 2,
    # --- broker-side log compaction (surge_tpu.log.compactor; compaction.md) ---
    # dirty-ratio scheduler: a pass runs when dirty/total >= min-dirty-ratio
    # AND dirty records >= min-dirty-records, checked every interval;
    # tombstones older than the retention are GC'd
    "surge.log.compaction.enabled": False,
    "surge.log.compaction.interval-ms": 30_000,
    "surge.log.compaction.min-dirty-ratio": 0.5,
    "surge.log.compaction.min-dirty-records": 64,
    "surge.log.compaction.tombstone-retention-ms": 60_000,
    # --- log broker replication (acks=all role, common reference.conf:112-124) ---
    # how long a commit waits for the follower ack before failing back to the
    # client (which retries the same txn_seq and re-joins the queued item)
    "surge.log.replication-ack-timeout-ms": 5_000,
    # min.insync.replicas analog (count INCLUDES the leader): a follower that
    # keeps failing for longer than the isr-timeout is dropped from the
    # in-sync set — commits then ack without it — as long as the set stays
    # >= min-insync. 1 (default) = availability over durability with RF=2
    # (a lone leader keeps accepting writes; the dead follower must catch_up
    # before it re-joins); 2 = strict acks=all (a dead follower blocks
    # commits until it returns, the pre-r5 behavior).
    "surge.log.replication-min-insync": 1,
    "surge.log.replication-isr-timeout-ms": 10_000,
    # rejoin under live traffic: an out-of-sync follower lagging by at most
    # this many records is re-synced BY THE LEADER (missing suffix pushed
    # through the ordered Replicate stream + dedup table) during its probe —
    # a one-shot operator catch_up can never converge while commits keep
    # landing. Beyond the cap (fresh/empty replicas) the follower stays out
    # until catch_up bulk-copies it. 0 disables auto-resync.
    "surge.log.replication-auto-resync-max-records": 10_000,
    # quorum acks: replicas (leader included) that must hold a commit before
    # it acks; 0 = every in-sync replica (strict acks=all). N < replicas
    # trades the straggler's ship timeout out of commit latency and gates
    # follower reads at the quorum-acked high-watermark.
    "surge.log.replication.min-insync-acks": 0,
    # pipelined transactions: how long the broker's in-order apply gate
    # waits for a missing predecessor txn_seq (a pipelined window arriving
    # out of order) before answering retriable — the client retries the
    # same seq, preserving exactly-once
    "surge.log.txn-inorder-timeout-ms": 3_000,
    # --- leader failover (KIP-101/KIP-279 epoch fencing; docs/operations.md) ---
    # a follower started with follower_of= may probe its leader and promote
    # itself once the prober declares it dead (probe-failures consecutive
    # failures at probe-interval). The declare threshold is the availability/
    # split-brain dial: promotion while the leader still serves forks the log.
    "surge.log.failover.auto-promote": False,
    "surge.log.failover.probe-interval-ms": 1_000,
    "surge.log.failover.probe-failures": 3,
    # a peer NEVER seen alive gets probe-failures x this grace before being
    # declared dead (a follower booting first must not promote over a leader
    # that is still starting; bounded so a truly absent leader still fails over)
    "surge.log.failover.bootstrap-grace-factor": 10,
    # --- quorum cluster (majority-vote promotion; docs/operations.md) ---
    # full symmetric cluster membership (comma-separated, the SAME list on
    # every broker); non-empty switches prober-declared leader death from
    # self-promotion to VoteLeader campaigns
    "surge.log.quorum.peers": "",
    "surge.log.quorum.vote-timeout-ms": 1_000,  # per-peer VoteLeader RPC
    "surge.log.quorum.vote-rounds": 5,  # campaign rounds before stand-down
    # --- cluster self-healing: membership, leadership spread, autobalancer ---
    # spread partition leadership round-robin across the membership as
    # topics are created (else: ClusterMeta op "spread" triggers it
    # explicitly); false keeps the PR-7 whole-broker leadership
    "surge.cluster.spread": False,
    # how long a member's ships must keep failing (past the ISR drop)
    # before the coordinator reassigns its led partitions to survivors
    "surge.cluster.reassign-grace-ms": 5_000,
    # autobalancer (surge_tpu/cluster/autobalancer.py): decision cadence,
    # the planned-move budget per window, per-partition move hysteresis,
    # the lead-count skew (max-min) that triggers a rebalance, and dry-run
    # (decide + flight-record, never move)
    "surge.cluster.balancer.interval-ms": 5_000,
    "surge.cluster.balancer.move-budget": 4,
    "surge.cluster.balancer.window-ms": 60_000,
    "surge.cluster.balancer.hysteresis-ms": 30_000,
    "surge.cluster.balancer.max-lead-skew": 1,
    "surge.cluster.balancer.dry-run": False,
    # --- flight recorder ---
    # directory the broker auto-dumps its flight ring to when the fault
    # plane hard-kills it ("" disables; live dumps via the DumpFlight RPC)
    "surge.log.flight.dump-dir": "",
    # --- FileLog WAL journal rotation ---
    # rotate commits.log (which embeds WAL payloads) once its durable bytes
    # exceed this: segments are fsynced first, then a frontier line opens the
    # fresh journal and os.replace GCs the old generation. 0 disables.
    "surge.log.journal-rotate-bytes": 64 << 20,
    # --- engine command lane (ISSUE 12: the de-asyncio'd fast path) ---
    # "direct": entity -> publisher handoff without per-command event-loop
    # machinery — pendings of one forming batch share a single BATCH-LEVEL
    # ack future (resolved once per group commit), a timed-out caller's
    # records stay queued and a same-request_id retry JOINS them (the
    # request-id dedup keeps exactly-once), and entities await publishes
    # through a bare timer wait instead of a wrapper task. "classic": the
    # PR-3 per-command future + cancel-withdraw machinery (the paired bench
    # arm, and the fallback if a workload depends on withdraw-on-timeout).
    "surge.producer.command-lane": "direct",
    # --- native broker hot path (csrc/txn.cc via log/native_gate) ---
    # operator kill-switch for the C++ batch path: Transact payload decode,
    # the in-order/dedup gate kernel, WAL journal formatting, the per-round
    # journal append, lazy segment materialization and the segment read
    # decoder. false (or an unbuilt csrc/) falls back to the bit-identical
    # pure-Python path everywhere.
    "surge.log.native.enabled": True,
    # --- fault-injection plane (surge_tpu.testing.faults) ---
    # a named plan (e.g. "flaky-network") or JSON rule list armed at broker/
    # FileLog construction; empty = no plane, hooks cost one attribute check.
    # Runtime arming: the broker's ArmFaults RPC (tools/chaos.py).
    "surge.log.faults.plan": "",
    "surge.log.faults.seed": 0,
    # --- health (common reference.conf:228-260) ---
    "surge.health.window-frequency-ms": 10_000,
    "surge.health.window-buffer-size": 10,
    "surge.health.signal-buffer-size": 25,
    "surge.health.supervisor-restart-max": 3,
    # --- event-loop starvation prober (execution-context-prober analog) ---
    "surge.event-loop-prober.enabled": True,
    "surge.event-loop-prober.interval-ms": 1_000,
    "surge.event-loop-prober.threshold-ms": 200,
    "surge.event-loop-prober.late-probes": 3,
    # --- feature flags (core reference.conf:64-71) ---
    "surge.feature-flags.experimental.enable-mesh-sharding": False,
    # alternative clustering backend (external shard allocation; the
    # enable-akka-cluster analog, core reference.conf:64-66)
    "surge.feature-flags.experimental.enable-cluster-sharding": False,
    "surge.feature-flags.experimental.disable-single-record-transactions": False,
    # --- control plane (cross-process membership/assignment service) ---
    "surge.control-plane.ping-interval-ms": 500,
    "surge.control-plane.member-timeout-ms": 3_000,
    # --- gRPC transport security (KafkaSecurityConfiguration analog) ---
    "surge.grpc.tls.enabled": False,
    "surge.grpc.tls.cert-file": "",
    "surge.grpc.tls.key-file": "",
    "surge.grpc.tls.root-ca-file": "",
    "surge.grpc.tls.require-client-auth": False,
    # --- engine ---
    "surge.engine.num-partitions": 8,
    "surge.engine.dr-standby-enabled": False,
    # engine-side flight-recorder ring size (events); the admin DumpFlight
    # RPC and BrokerStatus-style stats report occupancy + dropped count
    "surge.engine.flight-capacity": 1024,
    # --- saga / process-manager orchestration (surge_tpu.saga) ---
    # per-step dispatch deadline, forward retry budget and exponential
    # backoff base; compensations get their own (larger) budget because
    # exhausting it parks the saga in the dead letter. poll-interval paces
    # the driver's state re-reads; max-concurrent bounds simultaneous
    # participant dispatches across all drivers.
    "surge.saga.step-timeout-ms": 10_000,
    "surge.saga.step-max-attempts": 4,
    "surge.saga.step-backoff-ms": 100,
    "surge.saga.compensation-max-attempts": 6,
    "surge.saga.poll-interval-ms": 50,
    "surge.saga.max-concurrent": 512,
    # --- consistency observatory (observability/audit.py) ---
    # opt-in: the auditor is a supervised Controllable the engine only
    # starts when enabled. interval paces cycles; cohort-size bounds the
    # aggregates shadow-replayed per cycle; digest-enabled gates the
    # cross-replica digest compare; dedup-probe gates the exactly-once
    # replay probe (skipped automatically on transports without a seq gate)
    "surge.audit.enabled": False,
    "surge.audit.interval-ms": 2_000,
    "surge.audit.cohort-size": 8,
    "surge.audit.digest-enabled": True,
    "surge.audit.dedup-probe": True,
}


@dataclass
class Config:
    """Layered config: overrides > env > DEFAULTS."""

    overrides: dict[str, Any] = field(default_factory=dict)
    defaults: Mapping[str, Any] = field(default_factory=lambda: DEFAULTS)

    def get(self, key: str, fallback: Any = None) -> Any:
        if key in self.overrides:
            return self.overrides[key]
        env = os.environ.get(_env_key(key))
        if env is not None:
            return _coerce(env, self.defaults.get(key, fallback))
        if key in self.defaults:
            return self.defaults[key]
        return fallback

    def get_int(self, key: str, fallback: int = 0) -> int:
        return int(self.get(key, fallback))

    def get_float(self, key: str, fallback: float = 0.0) -> float:
        return float(self.get(key, fallback))

    def get_bool(self, key: str, fallback: bool = False) -> bool:
        v = self.get(key, fallback)
        if isinstance(v, str):
            return v.strip().lower() in ("1", "true", "yes", "on")
        return bool(v)

    def get_str(self, key: str, fallback: str = "") -> str:
        return str(self.get(key, fallback))

    def get_int_list(self, key: str, fallback: str = "") -> list[int]:
        raw = self.get_str(key, fallback)
        return [int(p) for p in raw.split(",") if p.strip()]

    def get_seconds(self, key: str, fallback_ms: int = 0) -> float:
        """Millisecond config value as seconds (asyncio sleeps take seconds)."""
        return self.get_int(key, fallback_ms) / 1000.0

    def with_overrides(self, overrides: Mapping[str, Any] | None = None, **kv: Any) -> "Config":
        """Layer overrides on top. Dotted keys go in ``overrides``; keyword args use
        underscore form (``surge_replay_time_chunk``) and are canonicalized against the
        known default keys (so they actually match what ``get`` reads)."""
        merged = dict(self.overrides)
        merged.update(overrides or {})
        canonical = {_env_key(k): k for k in self.defaults}
        for k, v in kv.items():
            merged[canonical.get(_env_key(k), k)] = v
        return Config(overrides=merged, defaults=self.defaults)


def _coerce(env_value: str, exemplar: Any) -> Any:
    """Coerce an env-var string to the type of the default it overrides."""
    if isinstance(exemplar, bool):
        return env_value.strip().lower() in ("1", "true", "yes", "on")
    if isinstance(exemplar, int):
        try:
            return int(env_value)
        except ValueError:
            return env_value
    if isinstance(exemplar, float):
        try:
            return float(env_value)
        except ValueError:
            return env_value
    return env_value


_DEFAULT = Config()


def default_config() -> Config:
    return _DEFAULT


# --- Typed accessor bundles (surge/internal/config/*.scala equivalents) ---


@dataclass(frozen=True)
class TimeoutConfig:
    """surge/internal/config/TimeoutConfig.scala equivalent."""

    ask_timeout_s: float
    publish_timeout_s: float

    @staticmethod
    def from_config(cfg: Config) -> "TimeoutConfig":
        return TimeoutConfig(
            ask_timeout_s=cfg.get_seconds("surge.aggregate.ask-timeout-ms"),
            publish_timeout_s=cfg.get_seconds("surge.aggregate.publish-timeout-ms"),
        )


@dataclass(frozen=True)
class RetryConfig:
    """surge/internal/config/RetryConfig.scala equivalent."""

    init_retry_interval_s: float
    init_fetch_retry_s: float
    init_max_attempts: int
    publish_max_retries: int

    @staticmethod
    def from_config(cfg: Config) -> "RetryConfig":
        return RetryConfig(
            init_retry_interval_s=cfg.get_seconds("surge.aggregate.init-retry-interval-ms"),
            init_fetch_retry_s=cfg.get_seconds("surge.aggregate.init-fetch-retry-ms"),
            init_max_attempts=cfg.get_int("surge.aggregate.init-max-attempts"),
            publish_max_retries=cfg.get_int("surge.aggregate.publish-max-retries"),
        )


@dataclass(frozen=True)
class BackoffConfig:
    """surge/internal/config/BackoffConfig.scala equivalent (BackoffSupervisor knobs)."""

    min_backoff_s: float = 0.1
    max_backoff_s: float = 10.0
    random_factor: float = 0.2
    max_retries: int = 3
