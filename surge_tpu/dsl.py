"""User-facing engine factories — the scaladsl/javadsl surface.

Mirrors ``SurgeCommand`` (modules/command-engine/scaladsl/src/main/scala/surge/scaladsl/
command/SurgeCommand.scala:24-70): ``create_engine(business_logic)`` builds a fully wired
:class:`~surge_tpu.engine.pipeline.SurgeEngine`; and ``SurgeEngineBuilder`` mirrors the
javadsl's ``SurgeCommandBuilder.withBusinessLogic(...).build()``
(javadsl/command/SurgeCommandBuilder.scala:9-22) for callers preferring fluent wiring.

The result ADTs (:class:`CommandSuccess` / :class:`CommandRejected` /
:class:`CommandFailure`) are re-exported here — scaladsl/common/AggregateRefResult.scala:5-11.
"""

from __future__ import annotations

from typing import Any, Optional

from surge_tpu.config import Config
from surge_tpu.engine.business_logic import SurgeCommandBusinessLogic
from surge_tpu.engine.entity import CommandFailure, CommandRejected, CommandSuccess
from surge_tpu.engine.partition import HostPort, PartitionTracker
from surge_tpu.engine.pipeline import EngineNotRunningError, EngineStatus, SurgeEngine

__all__ = [
    "CommandFailure",
    "CommandRejected",
    "CommandSuccess",
    "EngineNotRunningError",
    "EngineStatus",
    "SurgeCommandBusinessLogic",
    "SurgeEngine",
    "SurgeEngineBuilder",
    "create_engine",
]


def create_engine(business_logic: SurgeCommandBusinessLogic, *, log=None,
                  config: Optional[Config] = None,
                  local_host: Optional[HostPort] = None,
                  tracker: Optional[PartitionTracker] = None,
                  remote_deliver=None, mesh=None, tracer=None,
                  membership=None, shard_allocation=None) -> SurgeEngine:
    """Build (not start) an engine — ``SurgeCommand(businessLogic)`` equivalent.

    Single-node by default (in-memory log, self-assigned partitions); pass a shared
    ``tracker``/``remote_deliver`` for multi-node routing (SURVEY.md §2.10).
    With ``surge.feature-flags.experimental.enable-cluster-sharding`` the engine uses
    the external-shard-allocation backend; share ``membership``/``shard_allocation``
    across the cluster's engines (surge_tpu.engine.cluster)."""
    return SurgeEngine(business_logic, log=log, config=config, local_host=local_host,
                       tracker=tracker, remote_deliver=remote_deliver, mesh=mesh,
                       tracer=tracer, membership=membership,
                       shard_allocation=shard_allocation)


class SurgeEngineBuilder:
    """Fluent builder (javadsl SurgeCommandBuilder analog)."""

    def __init__(self) -> None:
        self._logic: Optional[SurgeCommandBusinessLogic] = None
        self._kwargs: dict[str, Any] = {}

    def with_business_logic(self, logic: SurgeCommandBusinessLogic) -> "SurgeEngineBuilder":
        self._logic = logic
        return self

    def with_log(self, log) -> "SurgeEngineBuilder":
        self._kwargs["log"] = log
        return self

    def with_config(self, config: Config) -> "SurgeEngineBuilder":
        self._kwargs["config"] = config
        return self

    def with_local_host(self, host: HostPort) -> "SurgeEngineBuilder":
        self._kwargs["local_host"] = host
        return self

    def with_tracker(self, tracker: PartitionTracker) -> "SurgeEngineBuilder":
        self._kwargs["tracker"] = tracker
        return self

    def with_tracer(self, tracer) -> "SurgeEngineBuilder":
        self._kwargs["tracer"] = tracer
        return self

    def with_mesh(self, mesh) -> "SurgeEngineBuilder":
        self._kwargs["mesh"] = mesh
        return self

    def with_membership(self, membership) -> "SurgeEngineBuilder":
        """Shared ClusterMembership for the cluster-sharding backend."""
        self._kwargs["membership"] = membership
        return self

    def with_shard_allocation(self, allocation) -> "SurgeEngineBuilder":
        """Shared ExternalShardAllocation for the cluster-sharding backend."""
        self._kwargs["shard_allocation"] = allocation
        return self

    def build(self) -> SurgeEngine:
        if self._logic is None:
            raise ValueError("business logic is required (with_business_logic)")
        return create_engine(self._logic, **self._kwargs)
