"""Command engine core — the L3/L4 equivalent of the reference's command-engine modules.

- :mod:`surge_tpu.engine.model` — processing-model API + TPU replay spec
  (scaladsl/command/CommandModels.scala:12-74).
- :mod:`surge_tpu.engine.business_logic` — the user bundle + serialization executor
  (SurgeCommandBusinessLogicTrait, internal/SurgeModel.scala).
- :mod:`surge_tpu.engine.entity` — per-aggregate single-writer FSM
  (internal/persistence/PersistentActor.scala).
- :mod:`surge_tpu.engine.publisher` — per-partition exactly-once publisher
  (internal/kafka/KafkaProducerActorImpl.scala).
- :mod:`surge_tpu.engine.shard` / :mod:`surge_tpu.engine.router` /
  :mod:`surge_tpu.engine.partition` — entity parents, partition routing, assignments
  (Shard.scala, KafkaPartitionShardRouterActor.scala, PartitionAssignments.scala).
- :mod:`surge_tpu.engine.ref` — AggregateRef client surface.
- :mod:`surge_tpu.engine.pipeline` — the wired engine (SurgeMessagePipeline.scala).
"""

from surge_tpu.engine.model import (
    AggregateCommandModel,
    AsyncAggregateCommandModel,
    AggregateEventModel,
    RejectedCommand,
    ReplayHandlers,
    ReplaySpec,
)
from surge_tpu.engine.business_logic import SurgeCommandBusinessLogic, SurgeModel
from surge_tpu.engine.entity import (
    AggregateEntity,
    ApplyEvents,
    CommandFailure,
    CommandRejected,
    CommandSuccess,
    Envelope,
    GetState,
    ProcessMessage,
)
from surge_tpu.engine.partition import (
    HostPort,
    PartitionAssignments,
    PartitionTracker,
    partition_for_key,
)
from surge_tpu.engine.pipeline import EngineNotRunningError, EngineStatus, SurgeEngine
from surge_tpu.engine.publisher import PartitionPublisher
from surge_tpu.engine.ref import AggregateRef
from surge_tpu.engine.router import SurgePartitionRouter
from surge_tpu.engine.shard import Shard

__all__ = [
    "AggregateCommandModel",
    "AggregateEntity",
    "AggregateEventModel",
    "AggregateRef",
    "ApplyEvents",
    "AsyncAggregateCommandModel",
    "CommandFailure",
    "CommandRejected",
    "CommandSuccess",
    "EngineNotRunningError",
    "EngineStatus",
    "Envelope",
    "GetState",
    "HostPort",
    "PartitionAssignments",
    "PartitionPublisher",
    "PartitionTracker",
    "ProcessMessage",
    "RejectedCommand",
    "ReplayHandlers",
    "ReplaySpec",
    "Shard",
    "SurgeCommandBusinessLogic",
    "SurgeEngine",
    "SurgeModel",
    "SurgePartitionRouter",
    "partition_for_key",
]
