"""Command engine core — the L3/L4 equivalent of the reference's command-engine modules.

- :mod:`surge_tpu.engine.model` — user-facing processing-model API
  (scaladsl/command/CommandModels.scala:12-74 equivalents) plus the TPU replay spec.
"""

from surge_tpu.engine.model import (
    AggregateCommandModel,
    AsyncAggregateCommandModel,
    AggregateEventModel,
    RejectedCommand,
    ReplayHandlers,
    ReplaySpec,
)

__all__ = [
    "AggregateCommandModel",
    "AsyncAggregateCommandModel",
    "AggregateEventModel",
    "RejectedCommand",
    "ReplayHandlers",
    "ReplaySpec",
]
