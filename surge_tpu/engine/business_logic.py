"""The user's business-logic bundle — what an application hands the engine.

Mirrors the reference's plugin surface (modules/command-engine/core/src/main/scala/surge/
core/commondsl/SurgeGenericBusinessLogicTrait.scala:16-64 +
SurgeCommandBusinessLogicTrait.scala:9-24): aggregate name, topics, formats, the
processing model, and engine-tuning hooks — plus (new) the model's TPU
:class:`~surge_tpu.engine.model.ReplaySpec` so the bulk-restore path can batch the fold.

Also the ``SurgeModel`` role (internal/SurgeModel.scala:20-66): async serialization of
events/state on a dedicated thread pool (``surge.serialization.thread-pool-size``).
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

from surge_tpu.config import Config, default_config
from surge_tpu.engine.model import ReplaySpec
from surge_tpu.log.transport import LogRecord


@dataclass
class SurgeCommandBusinessLogic:
    """Everything the engine needs to run one aggregate family."""

    aggregate_name: str
    model: Any  # AggregateCommandModel (sync) — process_command / handle_event
    state_format: Any  # AggregateRead+WriteFormatting
    event_format: Any  # EventRead+WriteFormatting
    # command ⇄ bytes codec; only required for cross-node delivery over the gRPC
    # node transport (the reference serializes envelopes with Jackson-CBOR for
    # akka-remoting the same way — optional because single-node engines never
    # serialize commands)
    command_format: Any = None
    state_topic: str = ""
    events_topic: str = ""
    publish_state_only: bool = False  # event-engine mode (no events topic)
    consumer_group_base: str = ""
    transactional_id_prefix: str = "surge"

    def __post_init__(self) -> None:
        if not self.state_topic:
            self.state_topic = f"{self.aggregate_name}-state"
        if not self.events_topic and not self.publish_state_only:
            self.events_topic = f"{self.aggregate_name}-events"
        if not self.consumer_group_base:
            self.consumer_group_base = f"{self.aggregate_name}-cg"

    def replay_spec(self) -> Optional[ReplaySpec]:
        """The model's TPU replay contract, if it opts in (ReplayableModel)."""
        fn = getattr(self.model, "replay_spec", None)
        return fn() if fn is not None else None


class SurgeModel:
    """Serialization executor around a business-logic bundle (SurgeModel.scala:20-66).

    ``serialize_outputs`` turns (aggregate_id, state, events) into the log records the
    publisher commits in one transaction: events first, the state snapshot last —
    off-thread on the shared pool so big JSON/proto payloads don't stall the event loop.
    """

    def __init__(self, logic: SurgeCommandBusinessLogic, config: Config | None = None,
                 pool: Optional[ThreadPoolExecutor] = None) -> None:
        self.logic = logic
        cfg = config or default_config()
        self._own_pool = pool is None
        # command-path fast path: short event batches serialize INLINE —
        # the executor hop (submit + wakeup) costs more than serializing a
        # small payload, and at engine throughput it is a per-command tax.
        # 0 keeps every batch off-thread (the reference's behavior).
        self._inline_max_events = cfg.get_int(
            "surge.serialization.inline-max-events", 4)
        self.pool = pool or ThreadPoolExecutor(
            max_workers=cfg.get_int("surge.serialization.thread-pool-size", 32),
            thread_name_prefix="surge-serde")

    async def serialize_outputs(self, aggregate_id: str, partition: int,
                                state: Any, events: Sequence[Any],
                                publish_state: bool = True) -> List[LogRecord]:
        import asyncio

        if len(events) <= self._inline_max_events > 0:
            return self._serialize_sync(aggregate_id, partition, state,
                                        list(events), publish_state)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self.pool, self._serialize_sync, aggregate_id, partition, state,
            list(events), publish_state)

    def _serialize_sync(self, aggregate_id: str, partition: int, state: Any,
                        events: List[Any], publish_state: bool) -> List[LogRecord]:
        records: List[LogRecord] = []
        if not self.logic.publish_state_only:
            for ev in events:
                msg = self.logic.event_format.write_event(ev)
                records.append(LogRecord(topic=self.logic.events_topic, key=msg.key,
                                         value=msg.value, partition=partition,
                                         headers=dict(msg.headers)))
        if publish_state:
            agg = self.logic.state_format.write_state(state)
            records.append(LogRecord(topic=self.logic.state_topic, key=aggregate_id,
                                     value=agg.value, partition=partition,
                                     headers=dict(agg.headers)))
        return records

    def deserialize_state(self, data: bytes) -> Any:
        return self.logic.state_format.read_state(data)

    def close(self) -> None:
        if self._own_pool:
            self.pool.shutdown(wait=False, cancel_futures=True)
