"""Alternative clustering backend: explicit membership + external shard allocation.

The reference ships two interchangeable routing backends selected by
``surge.feature-flags.experimental.enable-akka-cluster``
(core reference.conf:64-66, SurgePartitionRouterImpl.scala:34-161): the default
partition-sharding router, and Akka Cluster Sharding with an
``ExternalShardAllocationStrategy`` where shard id == partition number and a
rebalance listener drives allocations (KafkaClusterShardingRebalanceListener
.scala:17-183: join seeds with lowest-address bootstrap, update shard→member
allocations, start/stop per-partition regions).

TPU-native re-derivation (no Akka): plain registries on the event loop —

- :class:`ClusterMembership` — the member set; the lowest address is the leader
  (the "lowest-address node bootstraps the cluster" rule, :144-159).
- :class:`ExternalShardAllocation` — the explicit shard→member table + listeners
  (ExternalShardAllocationStrategy.updateShardLocations, :163-177).
- :class:`ClusterShardingRouter` — same delivery surface as
  :class:`~surge_tpu.engine.router.SurgePartitionRouter`, but ownership comes from
  the allocation table, and a partition-tracker listener (the rebalance listener
  role) lets THE LEADER translate partition assignments into allocations for the
  whole cluster (:83-116).

Engines select the backend with
``surge.feature-flags.experimental.enable-cluster-sharding``; multi-node setups
share one membership + allocation + tracker across engines (in one process for
tests, over the control plane in production).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Mapping, Optional

from surge_tpu.common import Ack, logger
from surge_tpu.engine.partition import (
    AssignmentChanges,
    HostPort,
    PartitionAssignments,
    PartitionTracker,
    partition_by_up_to_colon,
)
from surge_tpu.engine.router import RegionCreator, RemoteDeliver, RouterBase


class ClusterMembership:
    """Cluster member registry. Leader = lowest (host, port) — deterministic without
    coordination, mirroring the reference's lowest-address bootstrap rule."""

    def __init__(self) -> None:
        self._members: List[HostPort] = []
        self._listeners: List[Callable[[List[HostPort]], None]] = []

    @property
    def members(self) -> List[HostPort]:
        return list(self._members)

    @property
    def leader(self) -> Optional[HostPort]:
        return min(self._members) if self._members else None

    def join(self, member: HostPort) -> None:
        if member not in self._members:
            self._members.append(member)
            self._members.sort()
            self._broadcast()

    def leave(self, member: HostPort) -> None:
        if member in self._members:
            self._members.remove(member)
            self._broadcast()

    def subscribe(self, fn: Callable[[List[HostPort]], None]) -> None:
        self._listeners.append(fn)

    def unsubscribe(self, fn: Callable[[List[HostPort]], None]) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def _broadcast(self) -> None:
        for fn in list(self._listeners):
            try:
                fn(self.members)
            except Exception:  # noqa: BLE001
                logger.exception("membership listener failed")


class ExternalShardAllocation:
    """Explicit shard(=partition)→member table with change listeners."""

    def __init__(self) -> None:
        self._locations: Dict[int, HostPort] = {}
        self._listeners: List[Callable[[Mapping[int, HostPort]], None]] = []

    @property
    def locations(self) -> Dict[int, HostPort]:
        return dict(self._locations)

    def location_of(self, shard: int) -> Optional[HostPort]:
        return self._locations.get(shard)

    def update_shard_locations(self, mapping: Mapping[int, HostPort]) -> None:
        """updateShardLocations: merge the new shard→member entries and notify."""
        changed = {s: m for s, m in mapping.items()
                   if self._locations.get(s) != m}
        if not changed:
            return
        self._locations.update(changed)
        self._broadcast()

    def deallocate_member(self, member: HostPort) -> None:
        """Drop every shard allocated to ``member`` (it left the cluster); deliveries
        for those shards buffer until the leader re-allocates them."""
        dropped = [s for s, m in self._locations.items() if m == member]
        if not dropped:
            return
        for s in dropped:
            del self._locations[s]
        self._broadcast()

    def subscribe(self, fn: Callable[[Mapping[int, HostPort]], None]) -> None:
        self._listeners.append(fn)

    def unsubscribe(self, fn: Callable[[Mapping[int, HostPort]], None]) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    def _broadcast(self) -> None:
        for fn in list(self._listeners):
            try:
                fn(dict(self._locations))
            except Exception:  # noqa: BLE001
                logger.exception("shard allocation listener failed")


class ClusterShardingRouter(RouterBase):
    """Shard-allocation-driven router; delivery surface identical to
    :class:`SurgePartitionRouter` (both extend ``RouterBase``) so the engine can
    swap backends by flag. Shard id == partition number
    (KafkaShardingClassicMessageExtractor)."""

    health_name = "cluster-router"

    def __init__(self, num_partitions: int, tracker: PartitionTracker,
                 local_host: HostPort, region_creator: RegionCreator,
                 membership: Optional[ClusterMembership] = None,
                 allocation: Optional[ExternalShardAllocation] = None,
                 partition_by: Callable[[str], str] = partition_by_up_to_colon,
                 remote_deliver: Optional[RemoteDeliver] = None,
                 pending_limit: int = 1000) -> None:
        super().__init__(num_partitions, local_host, region_creator,
                         partition_by=partition_by, remote_deliver=remote_deliver,
                         pending_limit=pending_limit)
        self.tracker = tracker
        self.membership = membership if membership is not None else ClusterMembership()
        self.allocation = (allocation if allocation is not None
                           else ExternalShardAllocation())

    def owner_of(self, partition: int) -> Optional[HostPort]:
        return self.allocation.location_of(partition)

    # -- lifecycle ----------------------------------------------------------------------

    async def start(self) -> Ack:
        self._started = True
        self.allocation.subscribe(self._on_allocations)
        self.membership.subscribe(self._on_membership)
        self.tracker.register(self._on_assignments)
        self.membership.join(self.local_host)  # join seeds (:144-159)
        return Ack()

    async def stop(self) -> Ack:
        self._started = False
        self.tracker.unregister(self._on_assignments)
        self.membership.leave(self.local_host)
        self.membership.unsubscribe(self._on_membership)
        self.allocation.unsubscribe(self._on_allocations)
        await self._shutdown_regions()
        return Ack()

    # -- rebalance listener (KafkaClusterShardingRebalanceListener) ----------------------

    def _on_assignments(self, assignments: PartitionAssignments,
                        changes: AssignmentChanges) -> None:
        """Translate partition assignments into shard allocations — leader only
        (:163-177); every node then reacts to the allocation change."""
        if not self._started:
            return
        if self.membership.leader != self.local_host:
            return
        self.allocation.update_shard_locations(
            {p: hp for hp, parts in assignments.assignments.items() for p in parts})

    def _on_membership(self, members) -> None:
        """Departed members must not keep owning shards: the leader drops their
        allocations and re-derives placements from the current tracker assignments
        (deliveries for still-unowned shards buffer meanwhile)."""
        if not self._started or self.membership.leader != self.local_host:
            return
        member_set = set(members)
        for gone in {m for m in self.allocation.locations.values()
                     if m not in member_set}:
            self.allocation.deallocate_member(gone)
        live = {p: hp
                for hp, parts in self.tracker.assignments.assignments.items()
                for p in parts if hp in member_set}
        if live:
            self.allocation.update_shard_locations(live)

    def _on_allocations(self, locations: Mapping[int, HostPort]) -> None:
        if not self._started:
            return
        # stop regions for shards allocated away (:83-116 producer stop)
        for shard in [s for s in list(self._regions)
                      if locations.get(s) != self.local_host]:
            self._stop_region(shard, "re-allocated")
        # start regions for newly local shards; drain buffered deliveries
        for shard, owner in locations.items():
            if owner == self.local_host and shard not in self._regions:
                self._create_region(shard)
        self._drain_pending()

    # -- health -------------------------------------------------------------------------

    def health(self) -> dict:
        out = super().health()
        out["members"] = [str(m) for m in self.membership.members]
        out["leader"] = (str(self.membership.leader)
                         if self.membership.leader else None)
        return out
