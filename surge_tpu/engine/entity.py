"""Per-aggregate single-writer entity — the PersistentActor equivalent.

Re-derivation of the reference FSM (modules/command-engine/core/src/main/scala/surge/
internal/persistence/PersistentActor.scala:100-365) as one asyncio task per live
aggregate id, with the mailbox doubling as the stash:

- ``initializing``: the KTable init protocol (KTableInitializationSupport.scala:20-82) —
  poll ``is_aggregate_state_current`` on the partition publisher with bounded retries,
  then fetch + deserialize the snapshot from the state store; messages arriving
  meanwhile simply wait in the mailbox (the uninitialized-stash of
  PersistentActor.scala:174-195).
- ``free_to_process``: pop one envelope at a time (single-writer guarantee); commands run
  the user model, fold events, serialize off-thread, and
- ``persisting``: publish events + state through the partition publisher with the
  bounded retry ladder of KTablePersistenceSupport.scala:71-156 — same request id on
  every attempt (publisher dedup makes retries idempotent), timeout per attempt, and a
  **crash** after max retries (the parent recreates the entity, which re-reads state
  from the store — PersistentActor.onPersistenceFailure:357-364).
- idle passivation after ``surge.aggregate.idle-passivation-ms`` (:287-296), negotiated
  with the parent shard so late messages are buffered, never lost.

Error surface mirrors ACKSuccess/ACKError/ACKRejection (:33-64): domain rejections
(``RejectedCommand``) → :class:`CommandRejected`; model/fold/serialization exceptions →
:class:`CommandFailure` with the entity staying alive; persistence exhaustion →
:class:`CommandFailure` AND entity crash.
"""

from __future__ import annotations

# surgelint: fast-path-module — the per-command entity FSM (ISSUE 12)

import asyncio
import inspect
import uuid
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence

from surge_tpu.common import (DecodedState, cancel_safe_wait_for, fail_future,
                              logger, resolve_future, wait_future)
from surge_tpu.config import Config, RetryConfig, TimeoutConfig, default_config
from surge_tpu.engine.business_logic import SurgeModel
from surge_tpu.engine.model import RejectedCommand
from surge_tpu.engine.publisher import PartitionPublisher
from surge_tpu.metrics import EngineMetrics, engine_metrics

# fallback quiver for entities constructed outside a pipeline (tests, tools)
_DEFAULT_METRICS: EngineMetrics | None = None


def _default_metrics() -> EngineMetrics:
    global _DEFAULT_METRICS
    if _DEFAULT_METRICS is None:
        _DEFAULT_METRICS = engine_metrics()
    return _DEFAULT_METRICS


# -- message + result ADTs (PersistentActor.scala:33-64, AggregateRefResult.scala:5-11) --


#: envelope-header key carrying a caller-supplied request id (the saga
#: manager's deterministic saga-scoped rids). When present, the entity
#: publishes under THIS id instead of minting one — so a re-delivered
#: command after timeout/crash/failover dedups against the publisher's
#: completed window instead of folding twice.
REQUEST_ID_HEADER = "surge-request-id"


@dataclass
class ProcessMessage:
    command: Any


@dataclass
class GetState:
    pass


@dataclass
class ApplyEvents:
    events: Sequence[Any]


class Envelope:
    """One mailbox delivery. A plain __slots__ class, not a dataclass: one
    Envelope is built per command and the generated dataclass __init__ +
    default_factory machinery was measurable at engine throughput."""

    __slots__ = ("message", "reply", "headers")

    def __init__(self, message: Any, reply: "asyncio.Future[Any]",
                 headers: dict | None = None) -> None:
        self.message = message
        self.reply = reply
        self.headers = headers if headers is not None else {}  # trace context


@dataclass
class CommandSuccess:
    state: Any  # the post-command aggregate state (None = deleted/empty)


@dataclass
class CommandRejected:
    reason: Exception


@dataclass
class CommandFailure:
    error: Exception


class EntityCrashed(Exception):
    """The entity died mid-processing (persistence exhaustion or init failure)."""


class _Mailbox:
    """Minimal mailbox: a deque plus one waiter future. ``asyncio.Queue.get``
    under ``wait_for`` costs a wrapper task + timeout machinery per message —
    a real tax at engine throughput; here an idle entity parks on a bare
    future and the idle timeout is a single ``call_later`` handle."""

    __slots__ = ("_items", "_waiter")

    def __init__(self) -> None:
        from collections import deque

        self._items: "deque[Envelope]" = deque()
        self._waiter: Optional["asyncio.Future[Optional[Envelope]]"] = None

    def put_nowait(self, env: Envelope) -> None:
        w = self._waiter
        if w is not None and not w.done():
            self._waiter = None
            w.set_result(env)
        else:
            self._items.append(env)

    def empty(self) -> bool:
        return not self._items

    def get_nowait(self) -> Envelope:
        return self._items.popleft()

    async def get_or_idle(self, idle_s: float) -> Optional[Envelope]:
        """Next envelope, or None after ``idle_s`` with no delivery."""
        if self._items:
            return self._items.popleft()
        loop = asyncio.get_running_loop()
        waiter: "asyncio.Future[Optional[Envelope]]" = loop.create_future()
        self._waiter = waiter
        timer = loop.call_later(idle_s, resolve_future, waiter, None)
        try:
            return await waiter
        finally:
            timer.cancel()
            if self._waiter is waiter:
                self._waiter = None


class AggregateEntity:
    """One live aggregate: mailbox task + FSM state."""

    def __init__(self, aggregate_id: str, surge_model: SurgeModel,
                 publisher: PartitionPublisher,
                 fetch_state: Callable[[str], Optional[bytes]],
                 partition: int = 0, config: Config | None = None,
                 on_passivate: Callable[[str], None] | None = None,
                 on_stopped: Callable[[str, List[Envelope], bool], None] | None = None,
                 metrics: EngineMetrics | None = None, tracer=None) -> None:
        self.aggregate_id = aggregate_id
        self.surge_model = surge_model
        self.model = surge_model.logic.model
        # resolved once: process_command/handle_events run per command — the
        # attribute walk (and the handle_events getattr per fold) is pure
        # per-call overhead on the Transact path
        self._model_process = self.model.process_command
        self._model_batch_fold = getattr(self.model, "handle_events", None)
        self._model_fold = getattr(self.model, "handle_event", None)
        self.publisher = publisher
        self.fetch_state = fetch_state
        self.partition = partition
        self.config = config or default_config()
        self.on_passivate = on_passivate or (lambda agg_id: None)
        self.on_stopped = on_stopped or (lambda agg_id, leftovers, crashed: None)
        self.metrics = metrics or _default_metrics()
        self.tracer = tracer
        self.retry = RetryConfig.from_config(self.config)
        self.timeouts = TimeoutConfig.from_config(self.config)
        self._idle_s = self.config.get_seconds("surge.aggregate.idle-passivation-ms", 30_000)
        self.state_name = "created"
        self.state: Any = None
        self._mailbox = _Mailbox()
        self._task: Optional[asyncio.Task] = None
        # request-id source: one urandom draw per ENTITY (not per command —
        # uuid4's syscall is measurable at engine throughput); a restart makes
        # a fresh entity, so prefix+counter stays globally unique
        self._rid_prefix = uuid.uuid4().hex[:16]
        self._rid_n = 0

    # -- public surface -----------------------------------------------------------------

    def start(self) -> None:
        self._task = asyncio.ensure_future(self._run())
        self._task.set_name(f"entity-{self.aggregate_id}")

    def deliver(self, env: Envelope) -> None:
        if self.state_name == "stopped":
            raise EntityCrashed(f"entity {self.aggregate_id} is stopped")
        self._mailbox.put_nowait(env)

    async def stop(self) -> None:
        if self._task is not None and not self._task.done():
            self._task.cancel()
            try:
                await self._task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self.state_name = "stopped"

    # -- FSM ---------------------------------------------------------------------------

    async def _run(self) -> None:
        crashed = False
        try:
            await self._initialize()
            self.state_name = "free_to_process"
            while True:
                env = await self._mailbox.get_or_idle(self._idle_s)
                if env is None:
                    self.on_passivate(self.aggregate_id)  # parent starts buffering now
                    break
                try:
                    await self._handle(env)
                except _PersistenceExhausted:
                    crashed = True
                    break
            # drain any envelopes that arrived before passivation/crash was signalled
            while not self._mailbox.empty() and not crashed:
                try:
                    await self._handle(self._mailbox.get_nowait())
                except _PersistenceExhausted:
                    crashed = True
        except _InitFailed:
            crashed = True
        except asyncio.CancelledError:
            # externally stopped (shard/engine shutdown): fail queued callers promptly
            # rather than letting their asks ride out the full timeout
            self.state_name = "stopped"
            while not self._mailbox.empty():
                env = self._mailbox.get_nowait()
                fail_future(env.reply, EntityCrashed(
                    f"entity {self.aggregate_id} stopped"))
            raise
        finally:
            if self.state_name != "stopped":
                self.state_name = "stopped"
                leftovers = []
                while not self._mailbox.empty():
                    leftovers.append(self._mailbox.get_nowait())
                self.on_stopped(self.aggregate_id, leftovers, crashed)

    async def _initialize(self) -> None:
        """KTable init protocol: gate on publish lag, then fetch + deserialize."""
        self.state_name = "initializing"
        for attempt in range(self.retry.init_max_attempts):
            if not self.publisher.is_aggregate_state_current(self.aggregate_id):
                await asyncio.sleep(self.retry.init_retry_interval_s)
                continue
            try:
                with self.metrics.state_fetch_timer.time():
                    data = self.fetch_state(self.aggregate_id)
                    if inspect.isawaitable(data):
                        # async fetch backends (the device-resident state
                        # plane's batched gather lane) — the sync KV path
                        # never pays an await
                        data = await data
                if isinstance(data, DecodedState):
                    # the resident plane hands back an already-materialized
                    # domain state; re-serializing it through the byte
                    # contract would undo the gather's amortization
                    self.state = data.state
                    return
                with self.metrics.deserialization_timer.time():
                    self.state = (self.surge_model.deserialize_state(data)
                                  if data is not None else self._initial_state())
                return
            except Exception:  # noqa: BLE001 — fetch/deserialize failure retries
                logger.exception("state fetch failed for %s (attempt %d)",
                                 self.aggregate_id, attempt + 1)
                await asyncio.sleep(self.retry.init_fetch_retry_s)
        logger.error("init exhausted for aggregate %s", self.aggregate_id)
        raise _InitFailed()

    def _initial_state(self) -> Any:
        fn = getattr(self.model, "initial_state", None)
        return fn(self.aggregate_id) if fn is not None else None

    async def _handle(self, env: Envelope) -> None:
        # receive span, child of the ask span via traceparent headers
        # (ActorWithTracing's around-receive + PersistentActor.scala:166-168)
        span = None
        if self.tracer is not None:
            from surge_tpu.tracing import inject_context

            span = self.tracer.start_span(
                f"entity.{type(env.message).__name__}", headers=env.headers)
            # active for this entity task: the command/publish timers recorded
            # inside _handle_inner capture this trace as their exemplar
            span.activate()
            span.set_attribute("aggregate_id", self.aggregate_id)
            span.set_attribute("partition", self.partition)
            # downstream hops (the publisher's publish span) chain under the
            # receive span, completing the ref→router→shard→entity→publisher line
            env.headers = inject_context(span.context, env.headers)
        try:
            await self._handle_inner(env)
            if span is not None and env.reply.done() and not env.reply.cancelled():
                result = env.reply.exception() or env.reply.result()
                if isinstance(result, (CommandFailure, BaseException)):
                    span.status = "error"
        except BaseException as exc:
            if span is not None:
                span.record_exception(exc)
            raise
        finally:
            if span is not None:
                span.finish()

    async def _handle_inner(self, env: Envelope) -> None:
        msg = env.message
        if isinstance(msg, GetState):
            resolve_future(env.reply, self.state)
            return
        if isinstance(msg, ProcessMessage):
            await self._process_command(env, msg.command)
            return
        if isinstance(msg, ApplyEvents):
            await self._apply_events(env, msg.events)
            return
        fail_future(env.reply, TypeError(f"unknown message {type(msg).__name__}"))

    async def _process_command(self, env: Envelope, command: Any) -> None:
        # 1. user command handler (may reject). Async models (the reference's
        # AsyncAggregateCommandModel — e.g. the multilanguage bridge's gRPC
        # round-trip to the business app) return awaitables; the single-writer
        # guarantee holds because this entity task awaits inline.
        self.metrics.command_rate.record()
        rid = env.headers.get(REQUEST_ID_HEADER) if env.headers else None
        if rid is not None:
            # caller-supplied rid: short-circuit a re-delivery BEFORE the
            # model runs. Publish-level dedup alone is not enough here — a
            # re-run handler would fold its events into in-memory state a
            # second time while the log stays exactly-once.
            disposition_of = getattr(self.publisher, "request_disposition", None)
            disposition = disposition_of(rid) if disposition_of else None
            if disposition == "completed":
                resolve_future(env.reply, CommandSuccess(self.state))
                return
            if disposition == "in-flight":
                # the original attempt is still working its way through the
                # publisher (crashed-entity leftovers): the caller backs off
                # and retries once the outcome is known
                resolve_future(env.reply, CommandFailure(RuntimeError(
                    f"request {rid} still in flight")))
                return
        try:
            with self.metrics.command_handling_timer.time():
                result = self._model_process(self.state, command)
                if inspect.isawaitable(result):
                    result = await result
                events = list(result)
        except RejectedCommand as rej:
            self.metrics.rejection_rate.record()
            resolve_future(env.reply, CommandRejected(rej))
            return
        except Exception as exc:  # noqa: BLE001 — user code failure → error ACK
            self.metrics.error_rate.record()
            resolve_future(env.reply, CommandFailure(exc))
            return
        # 2. fold + persist + reply
        await self._fold_and_persist(env, events, reply_state=True)

    async def _apply_events(self, env: Envelope, events: Sequence[Any]) -> None:
        """applyEvents path (PersistentActor.doApplyEvent:245-264): fold externally
        produced events, publish the state snapshot only."""
        await self._fold_and_persist(env, list(events), reply_state=True,
                                     state_only=True)

    async def _fold_and_persist(self, env: Envelope, events: List[Any],
                                reply_state: bool, state_only: bool = False) -> None:
        old_state = self.state
        try:
            with self.metrics.event_handling_timer.time():
                batch_fold = self._model_batch_fold
                if batch_fold is not None:
                    # async/batch fold (AsyncAggregateCommandModel.handleEvents)
                    new_state = batch_fold(old_state, events)
                    if inspect.isawaitable(new_state):
                        new_state = await new_state
                else:
                    fold = self._model_fold
                    new_state = old_state
                    for ev in events:
                        new_state = fold(new_state, ev)
        except Exception as exc:  # noqa: BLE001 — fold failure → error ACK, no persist
            self.metrics.error_rate.record()
            resolve_future(env.reply, CommandFailure(exc))
            return

        if not events and not state_only:
            # no-op command: nothing to persist (reference skips publish when there are
            # no events and state is unchanged)
            resolve_future(env.reply, CommandSuccess(new_state))
            return

        self.state_name = "persisting"
        try:
            try:
                with self.metrics.serialization_timer.time():
                    records = await self.surge_model.serialize_outputs(
                        self.aggregate_id, self.partition, new_state,
                        [] if state_only else events)
            except Exception as exc:  # noqa: BLE001 — serialization failure → error ACK
                self.metrics.error_rate.record()
                resolve_future(env.reply, CommandFailure(exc))
                return

            request_id = env.headers.get(REQUEST_ID_HEADER) if env.headers else None
            if request_id is None:
                self._rid_n += 1
                request_id = f"{self._rid_prefix}-{self._rid_n}"
            last_error: Optional[Exception] = None
            for _ in range(self.retry.publish_max_retries + 1):
                try:
                    with self.metrics.publish_timer.time():
                        aw = self.publisher.publish(self.aggregate_id,
                                                    records, request_id,
                                                    headers=env.headers)
                        if isinstance(aw, asyncio.Future):
                            # bare ack future (the publish fast path): a
                            # slim timer wait, no wrapper task. A shared
                            # batch-level ack (direct lane) must never be
                            # cancelled by THIS caller's timeout — the
                            # records stay queued and the retry below joins
                            # them by request id.
                            await wait_future(
                                aw, self.timeouts.publish_timeout_s,
                                owned=not getattr(self.publisher,
                                                  "shared_acks", False))
                        else:
                            await cancel_safe_wait_for(
                                aw, timeout=self.timeouts.publish_timeout_s)
                    self.state = new_state
                    resolve_future(env.reply, CommandSuccess(new_state))
                    return
                except asyncio.TimeoutError as exc:
                    last_error = exc
                except Exception as exc:  # noqa: BLE001 — publish failure → retry
                    last_error = exc
            # retries exhausted: error the sender, then crash so the next message gets
            # a fresh entity re-initialized from the store
            resolve_future(env.reply, CommandFailure(
                last_error or RuntimeError("publish failed")))
            raise _PersistenceExhausted()
        finally:
            if self.state_name == "persisting":
                self.state_name = "free_to_process"


class _InitFailed(Exception):
    pass


class _PersistenceExhausted(Exception):
    pass
