"""Event-only engine DSL — the SurgeEvent surface.

Reference: the event-engine side of the scaladsl (scaladsl/event/SurgeEvent.scala:19-59,
AggregateEventModel.scala:10-38, SurgeEventServiceModel.scala:15-46): models implement
only the event fold (``handle_event`` / async batch ``handle_events``); there is no
command side — the engine publishes state snapshots only (no events topic), and the
client surface is ``apply_events`` + ``get_state`` (``sendCommand`` does not exist; the
core model's ``handle`` throws in the reference, AggregateEventModel.scala:24).
"""

from __future__ import annotations

from typing import Any, Optional

from surge_tpu.config import Config
from surge_tpu.engine.business_logic import SurgeCommandBusinessLogic
from surge_tpu.engine.pipeline import SurgeEngine


class _EventOnlyModel:
    """Adapts an event model (handle_event / handle_events, optional initial_state)
    to the engine's processing-model port, with the command side closed off."""

    def __init__(self, event_model: Any) -> None:
        self._inner = event_model
        handle_event = getattr(event_model, "handle_event", None)
        if handle_event is not None:
            self.handle_event = handle_event
        batch = getattr(event_model, "handle_events", None)
        if batch is not None:
            self.handle_events = batch
        if handle_event is None and batch is None:
            raise TypeError(
                f"{type(event_model).__name__} must define handle_event or "
                f"handle_events")
        replay = getattr(event_model, "replay_spec", None)
        if replay is not None:
            self.replay_spec = replay

    def initial_state(self, aggregate_id: str) -> Any:
        fn = getattr(self._inner, "initial_state", None)
        return fn(aggregate_id) if fn is not None else None

    def process_command(self, state: Any, command: Any):
        raise TypeError(
            "event engines do not process commands — use apply_events "
            "(AggregateEventModel.scala:24 throws the same way)")


def event_business_logic(aggregate_name: str, event_model: Any, state_format: Any,
                         **kwargs) -> SurgeCommandBusinessLogic:
    """SurgeEventServiceModel analog: state topic only, no events topic."""
    return SurgeCommandBusinessLogic(
        aggregate_name=aggregate_name, model=_EventOnlyModel(event_model),
        state_format=state_format, event_format=_NoEventFormat(),
        publish_state_only=True, **kwargs)


class _NoEventFormat:
    """Event engines never serialize events (publish_state_only short-circuits the
    events-topic path); reaching this is a wiring bug."""

    def write_event(self, event: Any):
        raise TypeError("event engines do not publish events")

    def read_event(self, msg: Any):
        raise TypeError("event engines do not read events")


class EventAggregateRef:
    """The event-engine client handle: apply_events + get_state only
    (scaladsl/event — no sendCommand exists on this surface)."""

    def __init__(self, inner) -> None:
        self._inner = inner
        self.aggregate_id = inner.aggregate_id

    async def apply_events(self, events):
        return await self._inner.apply_events(events)

    async def get_state(self) -> Optional[Any]:
        return await self._inner.get_state()


class SurgeEventEngine:
    """Thin wrapper giving the event-engine client surface over a SurgeEngine."""

    def __init__(self, engine: SurgeEngine) -> None:
        self.engine = engine

    def aggregate_for(self, aggregate_id: str) -> EventAggregateRef:
        return EventAggregateRef(self.engine.aggregate_for(aggregate_id))

    async def start(self):
        return await self.engine.start()

    async def stop(self):
        return await self.engine.stop()

    def health_check(self):
        return self.engine.health_check()

    @property
    def status(self):
        return self.engine.status


def create_event_engine(aggregate_name: str, event_model: Any, state_format: Any,
                        *, log=None, config: Optional[Config] = None,
                        **engine_kwargs) -> SurgeEventEngine:
    """``SurgeEvent(businessLogic)`` equivalent (scaladsl/event/SurgeEvent.scala:19-59)."""
    logic = event_business_logic(aggregate_name, event_model, state_format)
    return SurgeEventEngine(SurgeEngine(logic, log=log, config=config, **engine_kwargs))
