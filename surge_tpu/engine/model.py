"""User-facing processing-model API + the TPU replay contract.

Scalar side mirrors the reference's model family (scaladsl/command/CommandModels.scala:12-74):
``AggregateCommandModel`` (sync ``process_command``/``handle_event``),
``AsyncAggregateCommandModel``, and the event-engine-only ``AggregateEventModel``
(scaladsl/event/AggregateEventModel.scala:10-38). Rejections are exceptions
(``RejectedCommand``) rather than Try/Failure.

TPU side (**new — the point of this framework**): a model may attach a :class:`ReplaySpec`
declaring its tensor schemas and a per-event-type JAX step function. The replay engine
(surge_tpu.replay) lifts those steps into ``lax.switch`` inside a ``lax.scan`` over
time-major event columns, ``vmap``-ed across aggregates — the batched form of the
per-aggregate ``handleEvent`` fold at CommandModels.scala:20-27 / SURVEY.md §3.3.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Generic, Mapping, Optional, Protocol, Sequence, TypeVar

from surge_tpu.codec.schema import SchemaRegistry

S = TypeVar("S")
C = TypeVar("C")
E = TypeVar("E")

# A state "record" on the tensor path: dict of scalar jnp values, one per state column.
StateTree = Dict[str, Any]
# Event fields at one timestep: dict of scalar jnp values, one per union column.
EventFields = Mapping[str, Any]
# One event type's JAX step: (state, fields) -> state. Pure, traceable, scalar (the
# engine vmaps it across the aggregate batch).
JaxEventHandler = Callable[[StateTree, EventFields], StateTree]


class RejectedCommand(Exception):
    """Domain rejection of a command (reference: Failure(...) from processCommand,
    surfaced as CommandFailure — scaladsl/common/AggregateRefResult.scala:5-11)."""


class AggregateCommandModel(Protocol[S, C, E]):
    """Sync command model — scaladsl AggregateCommandModel (CommandModels.scala:12-31).

    ``process_command`` returns the events to persist (raise :class:`RejectedCommand` to
    reject); ``handle_event`` is the pure fold the engine applies — and the function the
    TPU replay path batches.
    """

    def initial_state(self, aggregate_id: str) -> Optional[S]:
        return None

    def process_command(self, state: Optional[S], command: C) -> Sequence[E]: ...

    def handle_event(self, state: Optional[S], event: E) -> Optional[S]: ...


class AsyncAggregateCommandModel(Protocol[S, C, E]):
    """Async variant — scaladsl AsyncAggregateCommandModel (CommandModels.scala:33-52).
    Used by the multilanguage bridge where handlers are RPCs to another process
    (GenericAsyncAggregateCommandModel.scala:14-104)."""

    def initial_state(self, aggregate_id: str) -> Optional[S]:
        return None

    async def process_command(self, state: Optional[S], command: C) -> Sequence[E]: ...

    async def handle_events(self, state: Optional[S], events: Sequence[E]) -> Optional[S]: ...


class AggregateEventModel(Protocol[S, E]):
    """Event-engine-only model — scaladsl/event/AggregateEventModel.scala:10-38.
    ``apply_events`` folds externally-produced events; there is no command side."""

    def initial_state(self, aggregate_id: str) -> Optional[S]:
        return None

    def apply_events(self, state: Optional[S], events: Sequence[E]) -> Optional[S]: ...


def fold_events(model: AggregateCommandModel, state: Optional[S], events: Sequence[E]) -> Optional[S]:
    """The scalar fold (reference: events.foldLeft at CommandModels.scala:20-21).

    Prefers per-event ``handle_event``; falls back to a synchronous batch
    ``handle_events``. Async-only models (e.g. the multilanguage gRPC model) cannot
    fold offline — bulk restore must go through a scalar-capable model."""
    import inspect

    handle_event = getattr(model, "handle_event", None)
    if handle_event is not None:
        for ev in events:
            state = handle_event(state, ev)
        return state
    batch = getattr(model, "handle_events", None)
    if batch is not None and not inspect.iscoroutinefunction(batch):
        return batch(state, list(events))
    raise TypeError(
        f"{type(model).__name__} has no synchronous fold (handle_event or "
        f"non-async handle_events) — offline replay/restore is unavailable for "
        f"async-only models")


# --------------------------------------------------------------------------------------
# TPU replay contract
# --------------------------------------------------------------------------------------


@dataclass(frozen=True)
class ReplayHandlers:
    """Per-event-type JAX step functions keyed by the registry's type_ids."""

    by_type_id: Mapping[int, JaxEventHandler]

    def ordered(self, num_types: int) -> list[JaxEventHandler]:
        """Dense handler table for ``lax.switch``; missing ids get identity."""
        identity: JaxEventHandler = lambda state, fields: state
        return [self.by_type_id.get(tid, identity) for tid in range(num_types)]


@dataclass
class ReplaySpec:
    """Everything the TPU replay engine needs to batch-fold one model family.

    - ``registry``: event/state tensor schemas (surge_tpu.codec.schema).
    - ``handlers``: the JAX form of ``handle_event``, split per event type.
    - ``init_record``: column values of the "empty" state (the ``None`` aggregate).
      Replay starts every aggregate here unless a snapshot carry is supplied.
    """

    registry: SchemaRegistry
    handlers: ReplayHandlers
    init_record: Dict[str, Any] = field(default_factory=dict)
    #: optional AssociativeFold (surge_tpu.replay.seqpar) — when present, the
    #: replay engine's ``auto`` tile backend folds each tile by lift +
    #: order-preserving tree reduction instead of a sequential time scan
    #: (~58 µs/step loop machinery on the v5e, BENCH_ONCHIP.json), and the
    #: time axis can shard across a mesh. Law-checked on first use.
    associative: Any = None

    def init_state_tree(self) -> StateTree:
        """Scalar init record with schema-complete columns (missing fields → 0)."""
        import numpy as np

        out: StateTree = {}
        for f in self.registry.state.fields:
            v = self.init_record.get(f.name, 0)
            out[f.name] = np.asarray(v, dtype=f.dtype)
        return out


class ReplayableModel(Protocol):
    """A model that supports the TPU replay backend (``replay_backend = "tpu"``,
    BASELINE.json north star). ``replay_spec`` is consulted by the state-store bulk
    restore and by surge_tpu.replay directly."""

    def replay_spec(self) -> ReplaySpec: ...
