"""Partitioning: key hashing, host assignments, and the partition tracker.

Reference semantics preserved exactly:

- ``partition_for_key`` = ``abs(murmur3_string_hash(key) % num_partitions)`` using
  Scala's ``MurmurHash3.stringHash`` (UTF-16 char-pair mixing, seed 0xf7ca7fd2) so a
  migrating application's aggregates land on the same partitions as under the reference
  (KafkaPartitioner.scala:7-9).
- ``PartitionStringUpToColon``: partition by the aggregate id up to the first ``:``
  (KafkaPartitioner.scala:35-42) — the default key→partition-string rule.
- ``PartitionAssignments.update`` returns the revoked/added diff per host
  (PartitionAssignments.scala:24-63) driving region lifecycle on rebalance.
- ``PartitionTracker``: single source of truth for partition→host assignments with
  registered listeners (KafkaConsumerStateTrackingActor.scala:39-118), re-expressed as a
  plain registry on the event loop (no actor ask needed).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from surge_tpu.common import logger

_MASK32 = 0xFFFFFFFF
_STRING_SEED = 0xF7CA7FD2  # scala.util.hashing.MurmurHash3.stringSeed


def _rotl32(x: int, r: int) -> int:
    return ((x << r) | (x >> (32 - r))) & _MASK32


def _mix_k(k: int) -> int:
    k = (k * 0xCC9E2D51) & _MASK32
    k = _rotl32(k, 15)
    return (k * 0x1B873593) & _MASK32


def _mix(h: int, k: int) -> int:
    h ^= _mix_k(k)
    h = _rotl32(h, 13)
    return (h * 5 + 0xE6546B64) & _MASK32


def murmur3_string_hash(s: str) -> int:
    """Scala MurmurHash3.stringHash: mixes UTF-16 code units two at a time. Returns a
    signed 32-bit int (negative values possible, as on the JVM)."""
    h = _STRING_SEED
    # UTF-16 code units (JVM chars): astral code points become surrogate pairs, so
    # length and pair-mixing match the JVM exactly
    data = s.encode("utf-16-be")
    units = [(data[i] << 8) | data[i + 1] for i in range(0, len(data), 2)]
    i = 0
    n = len(units)
    while i + 1 < n:
        h = _mix(h, ((units[i] << 16) + units[i + 1]) & _MASK32)
        i += 2
    if i < n:
        h ^= _mix_k(units[i])  # mixLast: no rotate/multiply round
    # finalizeHash(h, length): xor length then avalanche
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & _MASK32
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & _MASK32
    h ^= h >> 16
    return h - (1 << 32) if h >= (1 << 31) else h


def partition_for_key(key: str, num_partitions: int) -> int:
    """abs(hash % n) with JVM remainder semantics (KafkaPartitioner.scala:8)."""
    if num_partitions <= 0:
        raise ValueError("num_partitions must be positive")
    return abs(murmur3_string_hash(key)) % num_partitions


def partition_by_up_to_colon(aggregate_id: str) -> str:
    """Default partition-by rule (PartitionStringUpToColon, KafkaPartitioner.scala:35-42):
    ids like ``tenant:uuid`` co-locate per tenant."""
    idx = aggregate_id.find(":")
    return aggregate_id if idx < 0 else aggregate_id[:idx]


@dataclass(frozen=True, order=True)
class HostPort:
    """A node identity (PartitionAssignments.scala HostPort)."""

    host: str
    port: int

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"


Assignments = Dict[HostPort, List[int]]  # host -> partitions (single topic family)


@dataclass(frozen=True)
class AssignmentChanges:
    """Revoked/added partitions per host (PartitionAssignmentChanges.diff)."""

    revoked: Mapping[HostPort, List[int]]
    added: Mapping[HostPort, List[int]]


def _missing(a: Assignments, b: Assignments) -> Dict[HostPort, List[int]]:
    return {hp: [p for p in parts if p not in b.get(hp, [])]
            for hp, parts in a.items()}


@dataclass
class PartitionAssignments:
    """Current cluster assignment map + diffing update (PartitionAssignments.scala:50-63)."""

    assignments: Assignments = field(default_factory=dict)
    _p2h: Optional[Dict[int, HostPort]] = field(default=None, repr=False, compare=False)

    def partition_to_host(self) -> Dict[int, HostPort]:
        # cached: instances are replaced wholesale by update(), and this sits on the
        # per-message routing hot path
        if self._p2h is None:
            self._p2h = {p: hp for hp, parts in self.assignments.items() for p in parts}
        return self._p2h

    def update(self, new: Assignments) -> Tuple["PartitionAssignments", AssignmentChanges]:
        changes = AssignmentChanges(revoked=_missing(self.assignments, new),
                                    added=_missing(new, self.assignments))
        return PartitionAssignments(dict(new)), changes


class PartitionTracker:
    """Assignment registry + listener broadcast (KafkaConsumerStateTrackingActor)."""

    def __init__(self) -> None:
        self._current = PartitionAssignments()
        self._listeners: List[Callable[[PartitionAssignments, AssignmentChanges], None]] = []

    @property
    def assignments(self) -> PartitionAssignments:
        return self._current

    def register(self, listener: Callable[[PartitionAssignments, AssignmentChanges], None],
                 replay_current: bool = True) -> None:
        """Register + immediately deliver the current state (the tracker actor sends
        the registry state to new listeners, KafkaConsumerStateTrackingActor.scala:70-83)."""
        self._listeners.append(listener)
        if replay_current and self._current.assignments:
            listener(self._current, AssignmentChanges(revoked={},
                                                      added=self._current.assignments))

    def unregister(self, listener: Callable[[PartitionAssignments, AssignmentChanges], None]) -> None:
        """Stop broadcasting to ``listener`` (a stopped router must not keep creating
        regions off a shared tracker)."""
        try:
            self._listeners.remove(listener)
        except ValueError:
            pass

    def update(self, new: Assignments) -> AssignmentChanges:
        self._current, changes = self._current.update(new)
        for fn in list(self._listeners):
            try:
                fn(self._current, changes)
            except Exception:  # noqa: BLE001 — one listener must not break the broadcast
                logger.exception("assignment listener failed")
        return changes
