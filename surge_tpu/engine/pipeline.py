"""SurgeEngine — the wired engine object (SurgeMessagePipeline equivalent).

Reference: modules/command-engine/core/src/main/scala/surge/internal/domain/
SurgeMessagePipeline.scala:33-240 — constructs and owns the partition tracker, the
state-store indexer (KTable), the per-partition regions (publisher + shard), and the
router; implements ``Controllable`` start/stop/restart with an engine-status atomic
(SurgeEngineStatus.scala) and exposes ``aggregate_for`` (scaladsl/command/
SurgeCommand.scala:24-70).

Startup order follows :3.1's call stack: state-store indexer first, then router; in
single-node mode (no external control plane) the engine self-assigns every partition,
the PartitionTracker broadcast creates all local regions, and each region's publisher
runs its init-transactions + lag-gate protocol before serving. The optional
events-topic bulk restore (``surge.replay.restore-on-start``) runs the TPU replay
engine BEFORE indexing starts and fast-forwards the store watermarks — the
``replayBackend = tpu`` north star wired into the engine's cold start."""

from __future__ import annotations

import asyncio
import time
from enum import Enum
from typing import Callable, Dict, List, Optional

from surge_tpu.common import Ack, Controllable, DecodedState, logger
from surge_tpu.config import Config, default_config
from surge_tpu.engine.business_logic import SurgeCommandBusinessLogic, SurgeModel
from surge_tpu.engine.entity import AggregateEntity, Envelope
from surge_tpu.engine.partition import HostPort, PartitionTracker
from surge_tpu.engine.publisher import PartitionPublisher
from surge_tpu.engine.ref import AggregateRef
from surge_tpu.engine.router import SurgePartitionRouter
from surge_tpu.engine.shard import Shard
from surge_tpu.health import HealthCheck, HealthSignalBus, HealthSupervisor, RegexMatcher
from surge_tpu.log import InMemoryLog, TopicSpec
from surge_tpu.metrics import Metrics, engine_metrics
from surge_tpu.store import StateStoreIndexer, restore_from_events


class EngineStatus(Enum):
    """SurgeEngineStatus.scala equivalents."""

    STOPPED = "stopped"
    STARTING = "starting"
    RUNNING = "running"
    STOPPING = "stopping"
    FAILED = "failed"


class _Region:
    """One partition's publisher + shard (PersistentActorRegion.scala:26-116)."""

    def __init__(self, partition: int, publisher: PartitionPublisher, shard: Shard) -> None:
        self.partition = partition
        self.publisher = publisher
        self.shard = shard
        self._publisher_start = asyncio.ensure_future(self._start_with_retry())
        self._publisher_start.add_done_callback(self._on_publisher_started)

    async def _start_with_retry(self) -> None:
        """Publisher init with backoff (the BackoffSupervisor role around the
        reference's producer actor, AggregateStateStoreKafkaStreams.scala:
        106-118): a transient broker hiccup during open/flush-record must not
        leave the partition permanently unservable."""
        backoff = 0.2
        for attempt in range(5):
            try:
                await self.publisher.start()
                return
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 — retry transient init failures
                if attempt == 4:
                    raise
                logger.warning(
                    "publisher init failed for partition %d "
                    "(attempt %d/5, retrying in %.1fs): %r",
                    self.partition, attempt + 1, backoff, exc)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, 2.0)

    def _on_publisher_started(self, task: asyncio.Task) -> None:
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            logger.error("publisher init failed for partition %d: %r",
                         self.partition, exc)

    def deliver(self, aggregate_id: str, env: Envelope) -> None:
        self.shard.deliver(aggregate_id, env)

    async def stop(self) -> None:
        await self.shard.stop()
        if not self._publisher_start.done():
            self._publisher_start.cancel()
        await self.publisher.stop()


class SurgeEngine(Controllable):
    """A running engine for one aggregate family."""

    def __init__(self, logic: SurgeCommandBusinessLogic, log=None,
                 config: Config | None = None,
                 local_host: HostPort | None = None,
                 tracker: PartitionTracker | None = None,
                 remote_deliver=None, mesh=None, tracer=None,
                 membership=None, shard_allocation=None) -> None:
        self.logic = logic
        self.config = config or default_config()
        self.log = log if log is not None else InMemoryLog()
        self.local_host = local_host or HostPort("localhost", 0)
        self.mesh = mesh
        self.status = EngineStatus.STOPPED
        self.num_partitions = self.config.get_int("surge.engine.num-partitions", 8)
        self._external_tracker = tracker is not None
        self.tracker = tracker or PartitionTracker()

        self.log.create_topic(TopicSpec(logic.state_topic, self.num_partitions, compacted=True))
        if logic.events_topic:
            self.log.create_topic(TopicSpec(logic.events_topic, self.num_partitions))
        # observability plane: metrics registry + health signal bus + supervisor
        # (SurgeMessagePipeline wires the SlidingHealthSignalStreamProvider + Metrics
        # the same way, SurgeMessagePipeline.scala:56-87)
        # surge.metrics.exemplars: timers' histograms capture the active
        # trace id per recording (OpenMetrics exemplars — a p99 publish
        # bucket links to one JSONL trace). Opt-in: the engine hot path
        # records several timers per command.
        self.metrics_registry = Metrics(
            exemplars=self.config.get_bool("surge.metrics.exemplars", False))
        self.metrics = engine_metrics(self.metrics_registry)
        if getattr(self.log, "metrics", False) is None:
            # a broker-backed transport (GrpcLogTransport) counts its
            # failover rolls / NOT_LEADER redirects into this engine's
            # registry (surge.log.failover.*) unless the caller wired its own
            self.log.metrics = self.metrics
        self.tracer = tracer  # None = tracing disabled (zero per-message overhead)
        self.health_bus = HealthSignalBus(
            self.config.get_int("surge.health.signal-buffer-size", 25))
        self.health_supervisor = HealthSupervisor(self.health_bus, self.config)
        # engine-side flight recorder (the broker ring's twin): publisher
        # lane transitions, rebalance fan-out, resident-plane moves and
        # health-bus restarts land here; DumpFlight on the admin RPC pulls
        # the merge-ready envelope so engine + broker dumps interleave into
        # one cross-host incident timeline (tools/flight_timeline.py)
        from surge_tpu.observability.flight import FlightRecorder

        self.flight = FlightRecorder(
            capacity=self.config.get_int("surge.engine.flight-capacity", 1024),
            name=f"engine:{logic.aggregate_name}", role="engine")
        self.health_bus.subscribe(self._flight_health_signal)
        # refresh-round ledger (the device observatory): every resident-plane
        # fold round's padding-waste / per-stage anatomy and every gather
        # drain's device legs, in the flight envelope shape — DumpReplayLedger
        # pulls it, merge_dumps interleaves it with flight dumps, and
        # tools/roofline_record.py snapshots its summary
        from surge_tpu.replay.ledger import ReplayLedger

        self.replay_ledger = ReplayLedger(
            capacity=self.config.get_int(
                "surge.replay.resident.ledger-capacity", 512),
            name=f"engine:{logic.aggregate_name}")
        # tail-kept trace ring (the flight ring's trace twin, ISSUE 14):
        # install_tail attaches a TailSampler to the tracer so completed
        # traces that erred / breached surge.trace.tail.latency-ms / landed
        # in an SLO breach window are retained; the admin DumpTraces RPC
        # pulls the merge-ready envelope for cross-process anatomy assembly.
        # None when tracer=None (the tail plane costs nothing untraced).
        from surge_tpu.tracing.tail import install_tail

        self.trace_ring = install_tail(
            tracer, self.config, name=f"engine:{logic.aggregate_name}",
            role="engine", metrics=self.metrics)
        from surge_tpu.health.prober import EventLoopProber

        self.loop_prober = (EventLoopProber(
            self.config, on_signal=self.health_bus.signal_fn("event-loop"))
            if self.config.get_bool("surge.event-loop-prober.enabled") else None)
        self.surge_model = SurgeModel(logic, self.config)
        # saga / process-manager plane (surge_tpu.saga): attached via
        # register_saga_manager on the engine whose aggregates hold the saga
        # state machines; started/supervised with the pipeline lifecycle
        self.saga_manager = None
        self.indexer = StateStoreIndexer(self.log, logic.state_topic, config=self.config,
                                         on_signal=self.health_bus.signal_fn("state-store"))
        # routing backend selection by feature flag (SurgePartitionRouterImpl.scala:
        # 34-161 picks between the partition router and cluster sharding the same way)
        if self.config.get_bool("surge.feature-flags.experimental.enable-cluster-sharding"):
            from surge_tpu.engine.cluster import ClusterShardingRouter

            self.router = ClusterShardingRouter(
                num_partitions=self.num_partitions, tracker=self.tracker,
                local_host=self.local_host, region_creator=self._create_region,
                membership=membership, allocation=shard_allocation,
                remote_deliver=remote_deliver)
        else:
            self.router = SurgePartitionRouter(
                num_partitions=self.num_partitions, tracker=self.tracker,
                local_host=self.local_host, region_creator=self._create_region,
                remote_deliver=remote_deliver,
                dr_standby=self.config.get_bool("surge.engine.dr-standby-enabled"))
        self.router.tracer = tracer  # routing-hop spans (None = zero overhead)
        self.metrics_server = None  # started on demand by serve_metrics()
        self._rebalance_listeners: List[Callable] = []
        self._indexer_listener: Optional[Callable] = None
        # log compaction + state checkpoints (docs/compaction.md): the
        # compactor exists unconditionally so the admin CompactLog RPC can
        # always force a pass; its background scheduler only runs when enabled
        from surge_tpu.log.compactor import LogCompactor

        self.compactor = LogCompactor(
            self.log, config=self.config, topics=[logic.state_topic],
            metrics=self.metrics,
            on_signal=self.health_bus.signal_fn("log-compactor"))
        # device-resident materialized state plane (docs/replay.md): the
        # KTable-equivalent slab stays on device after the cold-start replay,
        # a standing refresh loop folds committed batches into it, and
        # getState / projections are answered from batched device gathers
        # with the host KV store as the staleness/coverage fallback
        self.resident_plane = None
        # incremental materialized views + changefeeds (docs/replay.md
        # "Materialized views"): registered scan queries the resident plane
        # folds every refresh round; None when no plane is wired — views NEED
        # the refresh feed, there is nothing to fold them from without it
        self.views = None
        if (self.config.get_bool("surge.replay.resident.enabled")
                and logic.events_topic):
            spec = logic.replay_spec()
            if spec is not None:
                from surge_tpu.replay.resident_state import ResidentStatePlane
                from surge_tpu.replay.views import MaterializedViews

                # the refresh feed's batch decoder (one C-level parse per
                # round) when the event format offers one; None keeps the
                # per-event path
                batch_read = getattr(logic.event_format,
                                     "read_events_batch", None)
                # counter-only profiler, ALWAYS wired (the un-gated "refresh"
                # umbrella): per-stage seconds/counts accumulate for the
                # observatory while the surge.replay.profile.* histograms
                # stay opt-in behind a DEBUG registry (sensor-level gating)
                from surge_tpu.replay.profiler import ReplayProfiler
                # engine-side fault plane (surge.log.faults.plan): arms the
                # corrupt.slab-row site for the corruption-to-page e2e; None
                # (the default) keeps every fault check a no-op
                from surge_tpu.testing.faults import FaultPlane

                self.resident_plane = ResidentStatePlane(
                    self.log, logic.events_topic, spec, config=self.config,
                    faults=FaultPlane.from_config(self.config),
                    partitions=[],  # assigned at start (follows the indexer)
                    deserialize_event=self._deserialize_event,
                    deserialize_events=batch_read,
                    serialize_state=lambda a, s: logic.state_format.write_state(s).value,
                    encode_event=getattr(logic, "encode_event", None),
                    decode_state=getattr(logic, "decode_state", None),
                    derived_cols=getattr(logic, "derived_cols", None),
                    mesh=self._resolve_mesh(), metrics=self.metrics,
                    on_signal=self.health_bus.signal_fn("resident-plane"),
                    profiler=ReplayProfiler.counters(metrics=self.metrics,
                                                     tracer=tracer),
                    flight=self.flight, ledger=self.replay_ledger,
                    tracer=tracer)
                self.views = MaterializedViews(
                    spec, config=self.config, mesh=self._resolve_mesh(),
                    metrics=self.metrics, ledger=self.replay_ledger,
                    flight=self.flight)
                self.resident_plane.attach_views(self.views)
        # consistency observatory (observability/audit.py): shadow-replays a
        # rotating cohort of resident rows against a from-scratch log refold,
        # compares cross-replica chained log digests, and probes the
        # exactly-once gate — findings page via the state-divergence SLO.
        # Digest peers join post-construction (engine.auditor.add_digest_peer)
        # since only cluster wiring knows the replica set.
        self.auditor = None
        if (self.resident_plane is not None
                and self.config.get_bool("surge.audit.enabled")):
            from surge_tpu.observability.audit import ConsistencyAuditor

            self.auditor = ConsistencyAuditor(
                self.resident_plane, log=self.log, config=self.config,
                metrics=self.metrics, flight=self.flight,
                on_signal=self.health_bus.signal_fn("consistency-auditor"))
            self.auditor.set_digest_targets(
                [(logic.events_topic, p)
                 for p in range(self.log.num_partitions(logic.events_topic))])
        self.checkpoint_writer = None
        ckpt_path = self.config.get_str("surge.store.checkpoint.path", "")
        if ckpt_path and logic.events_topic:
            from surge_tpu.store.checkpoint import (CheckpointStore,
                                                    CheckpointWriter)

            self._checkpoint_store = CheckpointStore(
                ckpt_path,
                keep=self.config.get_int("surge.store.checkpoint.keep", 2))
            self.checkpoint_writer = CheckpointWriter(
                self.log, logic.events_topic, logic.model,
                self._checkpoint_store,
                serialize_state=lambda a, s: logic.state_format.write_state(s).value,
                deserialize_event=self._deserialize_event,
                deserialize_state=logic.state_format.read_state,
                config=self.config, metrics=self.metrics,
                on_signal=self.health_bus.signal_fn("checkpoint-writer"))
        else:
            self._checkpoint_store = None

    # -- lifecycle (SurgeMessagePipeline.scala:185-240) ----------------------------------

    async def start(self) -> Ack:
        self.status = EngineStatus.STARTING
        try:
            if self.config.get_bool("surge.replay.restore-on-start"):
                await self.rebuild_from_events()
            # restart the state store on fatal signals (the restartSignalPatterns of
            # AggregateStateStoreKafkaStreams.scala:74-76)
            self.health_supervisor.register(
                "state-store", self.indexer,
                restart_patterns=[RegexMatcher(r"state-store.*fatal")])
            self.health_supervisor.start()
            if self.loop_prober is not None:
                self.loop_prober.start()
            # the indexer materializes only the partitions this node serves and
            # follows rebalances (Kafka Streams restores per assigned partition,
            # SURVEY.md §3.3; task migration §3.5); the listener is kept so
            # stop() can unregister it from a shared long-lived tracker
            self.indexer.set_partitions(self._indexer_partitions())
            if self._indexer_listener is None:
                self._indexer_listener = (
                    lambda _asg, _ch: self._retarget_partitions())
                self.tracker.register(self._indexer_listener, replay_current=False)
            await self.indexer.start()
            if self.resident_plane is not None:
                # the plane follows the same assignment as the indexer; it
                # seeds its slab from the events topic (cold-start replay that
                # stays on device), so it starts AFTER the indexer is tailing —
                # reads fall back to the host store until the seed lands
                self.resident_plane.set_partitions(self._indexer_partitions())
                await self.resident_plane.start()
                self.health_supervisor.register(
                    "resident-plane", self.resident_plane,
                    restart_patterns=[RegexMatcher(r"resident-plane.*fatal")])
            if self.config.get_bool("surge.log.compaction.enabled"):
                await self.compactor.start()
                self.health_supervisor.register(
                    "log-compactor", self.compactor,
                    restart_patterns=[RegexMatcher(r"log-compactor.*fatal")])
            if self.checkpoint_writer is not None:
                await self.checkpoint_writer.start()
                self.health_supervisor.register(
                    "checkpoint-writer", self.checkpoint_writer,
                    restart_patterns=[RegexMatcher(r"checkpoint-writer.*fatal")])
            await self.router.start()
            if self.saga_manager is not None:
                await self.saga_manager.start()
                self.health_supervisor.register(
                    "saga-manager", self.saga_manager,
                    restart_patterns=[RegexMatcher(r"saga-manager.*fatal")])
            if self.auditor is not None:
                await self.auditor.start()
                self.health_supervisor.register(
                    "consistency-auditor", self.auditor,
                    restart_patterns=[
                        RegexMatcher(r"consistency-auditor.*fatal")])
            if not self._external_tracker and not self.tracker.assignments.assignments:
                # single-node mode: self-assign every partition (no external control
                # plane; multi-node engines share an externally-updated tracker)
                self.tracker.update({self.local_host: list(range(self.num_partitions))})
            self.status = EngineStatus.RUNNING
            return Ack()
        except Exception:
            self.status = EngineStatus.FAILED
            # unwind partially-started observability tasks: a failed engine must not
            # leave the prober ticking or the supervisor subscribed forever
            if self._indexer_listener is not None:
                self.tracker.unregister(self._indexer_listener)
                self._indexer_listener = None
            self.health_supervisor.stop()
            if self.loop_prober is not None:
                await self.loop_prober.stop()
            raise

    def serve_metrics(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start the OpenMetrics HTTP scrape endpoint for this engine's
        registry (health-bus + supervisor counters included); returns the
        bound port. Stopped automatically by :meth:`stop`."""
        from surge_tpu.metrics.exposition import MetricsHTTPServer, health_collector

        if self.metrics_server is not None:
            return self.metrics_server.bound_port
        self.metrics_server = MetricsHTTPServer(
            self.metrics_registry, host=host, port=port,
            collectors=[health_collector(self.health_bus,
                                         self.health_supervisor)])
        return self.metrics_server.start()

    async def stop(self) -> Ack:
        self.status = EngineStatus.STOPPING
        if self.metrics_server is not None:
            # shutdown() blocks until the serve_forever poll notices (plus a
            # thread join) — off the event loop so in-flight replies never stall
            server, self.metrics_server = self.metrics_server, None
            await asyncio.get_running_loop().run_in_executor(None, server.stop)
        if self._indexer_listener is not None:
            self.tracker.unregister(self._indexer_listener)
            self._indexer_listener = None
        self.health_supervisor.stop()
        if self.loop_prober is not None:
            await self.loop_prober.stop()
        if self.auditor is not None:
            await self.auditor.stop()
        if self.saga_manager is not None:
            await self.saga_manager.stop()
        await self.router.stop()  # stops regions (shards + publishers)
        if self.views is not None:
            self.views.close()  # end changefeed subscriptions first
        if self.resident_plane is not None:
            await self.resident_plane.stop()
        await self.indexer.stop()
        await self.compactor.stop()
        if self.checkpoint_writer is not None:
            await self.checkpoint_writer.stop()
        self.surge_model.close()
        self.status = EngineStatus.STOPPED
        return Ack()

    async def shutdown(self) -> Ack:
        return await self.stop()

    # -- client surface ------------------------------------------------------------------

    def aggregate_for(self, aggregate_id: str) -> AggregateRef:
        """scaladsl SurgeCommand.aggregateFor (SurgeCommand.scala:52-54)."""
        return AggregateRef(aggregate_id, self._deliver_checked, self.config,
                            tracer=self.tracer)

    def _deliver_checked(self, aggregate_id: str, env: Envelope) -> None:
        if self.status != EngineStatus.RUNNING:
            raise EngineNotRunningError(
                f"engine status is {self.status.value} (SurgeEngineNotRunningException)")
        self.router.deliver(aggregate_id, env)

    # -- saga plane (surge_tpu.saga) -----------------------------------------------------

    def register_saga_manager(self, manager) -> None:
        """Attach a :class:`~surge_tpu.saga.manager.SagaManager` to this
        engine's lifecycle: started after the router, supervised under the
        ``saga-manager.*fatal`` restart pattern (a fired ``crash.saga.*``
        point restarts the manager, whose resume scan is the recovery path).
        Call before :meth:`start`; a manager registered on a running engine
        is started immediately by the caller."""
        if manager.on_signal is None:
            manager.on_signal = self.health_bus.signal_fn("saga-manager")
        if manager.metrics is None:
            manager.metrics = self.metrics
        if manager.flight is None:
            manager.flight = self.flight
        self.saga_manager = manager

    async def start_saga(self, saga_id: str, definition: str,
                         ctx=(0.0, 0.0, 0.0, 0.0)):
        """Admin-plane delegate → :meth:`SagaManager.start_saga`."""
        if self.saga_manager is None:
            raise RuntimeError("no saga manager registered on this engine")
        return await self.saga_manager.start_saga(saga_id, definition, ctx)

    async def saga_status(self, saga_id: str = ""):
        """Admin-plane delegate: one saga's ledger, or the fleet summary
        (counts + reconciliation verdict) when ``saga_id`` is empty."""
        if self.saga_manager is None:
            raise RuntimeError("no saga manager registered on this engine")
        if saga_id:
            return await self.saga_manager.status(saga_id)
        return self.saga_manager.summary()

    def audit_status(self) -> dict:
        """Admin-plane delegate: the consistency auditor's verdict
        (``ok`` is False while any divergence is unresolved)."""
        if self.auditor is None:
            raise RuntimeError("consistency auditor not enabled on this "
                               "engine (surge.audit.enabled)")
        return self.auditor.summary()

    def register_rebalance_listener(self, listener: Callable) -> None:
        """listener(assignments, changes) on every tracker update
        (registerRebalanceListener, SurgeMessagePipeline.scala:93-95)."""
        self.tracker.register(listener)

    # -- regions -------------------------------------------------------------------------

    def _flight_health_signal(self, signal) -> None:
        """Health-bus tap for the flight ring: restarts and error-level
        signals are incident-timeline material; trace/warning chatter is not
        (the bounded ring must survive to the post-mortem)."""
        if (signal.level == "error"
                or signal.name.startswith("health.component-")):
            self.flight.record("health.signal", name=signal.name,
                               level=signal.level, source=signal.source)

    def _retarget_partitions(self) -> None:
        """Rebalance fan-out: the indexer AND the resident plane follow the
        tracker's view of this node's partitions together, so the plane's
        fold watermarks always cover exactly what the host store tails."""
        prev = set(self.indexer.partitions)
        parts = self._indexer_partitions()
        if set(parts) != prev:
            self.flight.record("rebalance.retarget",
                               granted=sorted(set(parts) - prev),
                               revoked=sorted(prev - set(parts)))
        self.indexer.set_partitions(parts)
        if self.resident_plane is not None:
            self.resident_plane.set_partitions(parts)

    def _fetch_state(self, aggregate_id: str):
        """Entity-init state fetch: the resident plane first (one coalesced
        device gather, ``require_current`` — a command folded on stale state
        would fork the aggregate), host KV store on any miss. Sync KV path
        when no plane is wired (the entity never awaits then)."""
        if self.resident_plane is None or not self.resident_plane.running:
            return self.indexer.get_aggregate_bytes(aggregate_id)

        async def fetch():
            hit, state = await self.resident_plane.read_state(
                aggregate_id, require_current=True)
            if hit:
                return DecodedState(state)
            return self.indexer.get_aggregate_bytes(aggregate_id)

        return fetch()

    async def project_states(self, aggregate_ids, *,
                             require_current: bool = False) -> Dict[str, object]:
        """Read-side projection over many aggregates: every resident hit rides
        ONE batched device gather + a single fetch-barriered pull; misses
        (not resident, stale beyond ``surge.replay.resident.max-lag-records``,
        revoked, or no plane at all) are served from the host KV store.
        Returns ``{aggregate_id: state}``, omitting ids with no state."""
        out: Dict[str, object] = {}
        missing = list(aggregate_ids)
        if self.resident_plane is not None and self.resident_plane.running:
            hits = await self.resident_plane.project(
                missing, require_current=require_current)
            out.update(hits)
            missing = [a for a in missing if a not in hits]
        for agg in missing:
            data = self.indexer.get_aggregate_bytes(agg)
            if data is not None:
                out[agg] = self.logic.state_format.read_state(data)
        return out

    def _create_region(self, partition: int) -> _Region:
        if partition not in self.indexer.partitions:
            # a region implies serving this partition: its publisher's lag gate
            # needs the indexer tailing it even if the tracker view disagrees
            self.indexer.set_partitions(
                sorted(set(self.indexer.partitions) | {partition}))
            if self.resident_plane is not None:
                self.resident_plane.set_partitions(self.indexer.partitions)
        publisher = PartitionPublisher(
            self.log, self.logic.state_topic, self.logic.events_topic or None,
            partition, self.indexer, config=self.config,
            transactional_id_prefix=self.logic.transactional_id_prefix,
            still_owner=lambda p=partition: (
                self.tracker.assignments.partition_to_host().get(p) == self.local_host),
            on_signal=self.health_bus.signal_fn(f"publisher-{partition}"),
            metrics=self.metrics, tracer=self.tracer, flight=self.flight)
        shard = Shard(
            f"{self.logic.aggregate_name}-{partition}",
            lambda aggregate_id, on_passivate, on_stopped: AggregateEntity(
                aggregate_id, self.surge_model, publisher,
                fetch_state=self._fetch_state, partition=partition,
                config=self.config, on_passivate=on_passivate, on_stopped=on_stopped,
                metrics=self.metrics, tracer=self.tracer),
            buffer_limit=self.config.get_int("surge.aggregate.passivation-buffer-limit", 1000),
            tracer=self.tracer)
        return _Region(partition, publisher, shard)

    # -- health -------------------------------------------------------------------------

    def health_check(self) -> HealthCheck:
        """Engine → router → regions ask-chain (SurgeHealthCheck analog,
        KafkaPartitionShardRouterActor.getHealthCheck:353-366). Also refreshes the
        live-entity gauge."""
        regions = []
        live = 0
        for p, region in self.router.regions():
            live += region.shard.num_live_entities
            pub_ok = region.publisher.state == "processing"
            regions.append(HealthCheck(
                name=f"region-{p}",
                status="up" if pub_ok else "degraded",
                components=[HealthCheck(name=f"publisher-{p}",
                                        status="up" if pub_ok else "down")]))
        self.metrics.live_entities.record(live)
        # unconditional: a promoted node (standby set now empty) must read 0,
        # not its last pre-promotion lag
        self.metrics.standby_lag.record(
            self.indexer.lag_for(self.standby_partitions()))
        router_h = self.router.health()
        components = [
            HealthCheck(name="router",
                        status="up" if router_h["status"] == "up" else "down",
                        components=regions),
            HealthCheck(name="state-store",
                        status="up" if self.indexer.running else "down"),
        ]
        if self.resident_plane is not None:
            # degraded, not down: reads fall back to the host store, the
            # engine keeps serving
            components.append(HealthCheck(
                name="resident-plane",
                status="up" if self.resident_plane.running else "degraded"))
        if self.auditor is not None:
            # degraded-not-down while a divergence is unresolved: the page
            # means "read the flight timeline", never "restart over it"
            components.append(self.auditor.health_component())
        return HealthCheck(
            name=self.logic.aggregate_name,
            status="up" if self.status == EngineStatus.RUNNING else "down",
            components=components)

    def producer_stats(self) -> Dict[str, float]:
        """Aggregated group-commit lane stats across this node's partitions
        (the operator view of the adaptive publisher: how well batching and
        pipelining are doing). Sums counters, maxes peaks."""
        out = {"flushes": 0, "records_published": 0, "batches_failed": 0,
               "fences": 0, "reinitializations": 0, "dedup_hits": 0,
               "max_batch_records": 0, "inflight_peak": 0, "lanes": 0}
        for _p, region in self.router.regions():
            s = region.publisher.stats
            out["lanes"] += 1
            out["flushes"] += s.flushes
            out["records_published"] += s.records_published
            out["batches_failed"] += s.batches_failed
            out["fences"] += s.fences
            out["reinitializations"] += s.reinitializations
            out["dedup_hits"] += s.dedup_hits
            out["max_batch_records"] = max(out["max_batch_records"],
                                           s.max_batch_records)
            out["inflight_peak"] = max(out["inflight_peak"], s.inflight_peak)
        if out["flushes"]:
            out["records_per_flush"] = round(
                out["records_published"] / out["flushes"], 2)
        return out

    def owned_partitions(self) -> List[int]:
        """The partitions this node owns per the tracker — or ALL partitions when
        no assignments exist yet (single-node cold start self-assigns everything;
        a multi-node engine's external tracker is populated by the control plane
        before start)."""
        mapping = self.tracker.assignments.partition_to_host()
        if not mapping:
            return list(range(self.num_partitions))
        return sorted(p for p, h in mapping.items() if h == self.local_host)

    def _indexer_partitions(self) -> List[int]:
        """Partitions the state-store indexer must tail: owned ones, any with a
        live local region (a direct node-transport delivery can create a region
        the tracker view disclaims mid-rebalance — its publisher lag gate still
        needs the watermark to advance), plus this node's standby set. A region
        partition revoked later keeps tailing until the next assignment update;
        harmless, just idle reads."""
        parts = set(self.owned_partitions())
        parts.update(p for p, _ in self.router.regions())
        parts.update(self.standby_partitions())
        return sorted(parts)

    def standby_partitions(self) -> List[int]:
        """Partitions this node keeps a WARM standby copy of (Kafka Streams
        num.standby.replicas, SurgeStateStoreConsumer.scala:42 + common
        reference.conf:24-25): for each partition, the N hosts following its
        owner on the sorted-host ring tail it too, so a rebalance that promotes
        this node needs no state-topic re-read — the store rows and watermark
        are already current."""
        n = self.config.get_int("surge.state-store.num-standby-replicas", 0)
        if n <= 0:
            return []
        hosts = sorted(self.tracker.assignments.assignments)
        if self.local_host not in hosts or len(hosts) < 2:
            return []
        rank = {h: i for i, h in enumerate(hosts)}
        mine = rank[self.local_host]
        out = []
        for p, owner in self.tracker.assignments.partition_to_host().items():
            if owner == self.local_host:
                continue
            gap = (mine - rank[owner]) % len(hosts)
            if 1 <= gap <= min(n, len(hosts) - 1):
                out.append(p)
        return sorted(out)

    # -- TPU bulk restore ---------------------------------------------------------------

    def _resolve_mesh(self):
        """The replay mesh: an explicit ``mesh=`` wins; otherwise the
        enable-mesh-sharding feature flag builds a 1-D ``data`` mesh over every
        visible device (entity-parallel replay across the chip/pod, SURVEY.md
        §2.10)."""
        if self.mesh is not None:
            return self.mesh
        if not self.config.get_bool(
                "surge.feature-flags.experimental.enable-mesh-sharding"):
            return None
        import jax
        import numpy as _np

        devices = jax.devices()
        if len(devices) < 2:
            return None  # a 1-device mesh adds sharding overhead for nothing
        axis = (self.config.get_str("surge.replay.mesh-axes", "data")
                .split(",")[0].strip() or "data")  # must match ReplayEngine's axis
        self.mesh = jax.sharding.Mesh(_np.asarray(devices), (axis,))
        return self.mesh

    async def rebuild_from_events(self):
        """Traced wrapper around :meth:`_rebuild_from_events_inner` — the bulk
        restore is the engine's single heaviest operation, so it gets a span of
        its own (root unless the caller nests it)."""
        if self.tracer is None:
            return await self._rebuild_from_events_inner()
        with self.tracer.start_span("engine.rebuild-from-events") as span:
            result = await self._rebuild_from_events_inner()
            span.set_attribute("num_events", result.num_events)
            span.set_attribute("num_aggregates", result.num_aggregates)
            span.set_attribute("backend", result.backend)
            return result

    async def _rebuild_from_events_inner(self):
        """Rebuild the materialized store by folding the events topic through the
        configured replay backend, then bring the indexer current.

        Two paths:
        - ``surge.replay.segment-path`` set → **columnar segment restore** (the
          100M-event-scale path): build the segment once if absent (events topic →
          struct-of-arrays chunks + state-only snapshot carry), then stream it
          through the batched ReplayEngine with no per-event Python objects, and
          prime the indexer at the segment's build-time state watermarks so
          tail-indexing covers everything since (events+state commit atomically, so
          every post-build change has a post-watermark snapshot).
        - otherwise → the object-based fold (small-topic fallback) + a full
          state-topic snapshot overlay.
        """
        if not self.logic.events_topic:
            raise ValueError("rebuild_from_events requires an events topic")
        evt_fmt = self.logic.event_format
        state_fmt = self.logic.state_format
        from surge_tpu.serialization import SerializedMessage

        spec = self.logic.replay_spec()
        mesh = self._resolve_mesh()
        # restore ONLY the partitions this node serves (the reference restores per
        # assigned task, SURVEY.md §3.3 — active AND standby tasks): a multi-node
        # cold start does 1/N (+standbys) of the work and never writes unrelated
        # nodes' aggregates into the local store
        owned = sorted(set(self.owned_partitions()) | set(self.standby_partitions()))

        rebuild_t0 = time.monotonic()
        segment_path = self.config.get_str("surge.replay.segment-path", "")
        if segment_path:
            result = await asyncio.get_running_loop().run_in_executor(
                None, lambda: self._rebuild_from_segment(
                    segment_path, spec, mesh, owned))
            if result.watermarks:  # snapshot-carrying segment: no full state scan
                # already scoped to `owned`: restore_from_segment filters its
                # returned watermarks by the partitions it was given
                watermarks = result.watermarks
                # Segment states are BUILD-time states. Wherever the indexer has
                # already advanced past the build watermark (warm rebuild, or the
                # tail loop ran concurrently with the restore), those snapshots
                # will never be re-read after prime()'s max() — re-apply exactly
                # that window so the restore cannot revert the store to stale
                # values (advisor r3 finding #2). Cold starts have watermark 0
                # everywhere and skip this entirely.
                self._replay_state_window(watermarks)
                self.indexer.prime(watermarks)
            else:  # segment built without a state topic: overlay + prime at now
                self._overlay_snapshots_and_prime(owned)
            self._record_replay_metrics(result, rebuild_t0)
            logger.info("rebuild_from_events: %d aggregates from %d events via %s",
                        result.num_aggregates, result.num_events, result.backend)
            return result

        # checkpointed cold start: fold only the tail past the newest durable
        # checkpoint's watermarks (docs/compaction.md). None when no checkpoint
        # store is configured or none has been written yet — then the fold
        # runs from offset 0 exactly as before. latest() reads + decodes the
        # whole checkpoint file, so it runs in the executor with the fold.
        result = await asyncio.get_running_loop().run_in_executor(None, lambda: restore_from_events(
            self.log, self.logic.events_topic, self.indexer.store,
            deserialize_event=self._deserialize_event,
            serialize_state=lambda agg_id, st: state_fmt.write_state(st).value,
            model=self.logic.model, replay_spec=spec,
            encode_event=getattr(self.logic, "encode_event", None),
            decode_state=getattr(self.logic, "decode_state", None),
            config=self.config, mesh=mesh, partitions=owned,
            checkpoint=(self._checkpoint_store.latest()
                        if self._checkpoint_store is not None else None),
            deserialize_state=state_fmt.read_state,
            encode_state=getattr(self.logic, "encode_state", None)))
        self._overlay_snapshots_and_prime(owned)
        self._record_replay_metrics(result, rebuild_t0)
        logger.info("rebuild_from_events: %d aggregates from %d events via %s",
                    result.num_aggregates, result.num_events, result.backend)
        return result

    def _deserialize_event(self, raw: bytes):
        from surge_tpu.serialization import SerializedMessage

        return self.logic.event_format.read_event(
            SerializedMessage(key="", value=raw))

    def _record_replay_metrics(self, result, t0: float) -> None:
        """Feed the predeclared replay instruments (SURVEY §5.5): fold wall
        time and achieved events/s of the bulk rebuild."""
        elapsed = max(time.monotonic() - t0, 1e-9)
        self.metrics.replay_timer.record_ms(elapsed * 1000.0)
        self.metrics.replay_events_per_sec.record(result.num_events / elapsed)

    def _replay_state_window(self, build_watermarks: Dict[int, int]) -> None:
        """Re-apply state-topic records in [build watermark, current indexer
        watermark) per partition — the window a segment restore just clobbered and
        the tail loop will not revisit. Latest-wins with tombstone deletes, same
        as the indexer's own apply path."""
        store = self.indexer.store
        for p in build_watermarks:
            start = build_watermarks.get(p, 0)
            current = self.indexer.indexed_watermark(self.logic.state_topic, p)
            if current <= start:
                continue
            for r in self.log.read(self.logic.state_topic, p, start):
                if r.offset >= current or r.key is None:
                    continue
                if r.value is None:
                    store.delete(r.key)
                else:
                    store.put(r.key, r.value)

    def _overlay_snapshots_and_prime(self, partitions: List[int] | None = None) -> None:
        """Overlay the state topic's latest snapshot per key (for ``partitions``,
        default all) and prime the indexer at the current end offsets. Latest-wins
        unconditionally: events+state commit atomically, so a snapshot is always ≥
        any state replayed from events it covers — this both fills in state-only
        aggregates (apply_events) and corrects states replayed from a stale
        externally-built segment."""
        store = self.indexer.store
        parts = list(range(self.num_partitions)) if partitions is None else partitions
        for p in parts:
            for key, rec in self.log.latest_by_key(self.logic.state_topic, p).items():
                if rec.value is None:  # tombstone, same as the indexer's tail path
                    store.delete(key)
                else:
                    store.put(key, rec.value)
        self.indexer.prime({p: self.log.end_offset(self.logic.state_topic, p)
                            for p in parts})

    def _rebuild_from_segment(self, segment_path: str, spec, mesh,
                              owned: List[int] | None = None):
        """Blocking half of the segment rebuild (runs in the executor): build the
        segment if absent (always covering EVERY partition — it is a shared
        artifact), then stream-restore only this node's ``owned`` partitions'
        chunks from it."""
        from surge_tpu.store.restore import restore_from_segment

        state_fmt = self.logic.state_format
        self._ensure_segment(segment_path, spec)
        return restore_from_segment(
            segment_path, self.indexer.store, replay_spec=spec,
            serialize_state=lambda agg_id, st: state_fmt.write_state(st).value,
            decode_state=getattr(self.logic, "decode_state", None),
            config=self.config, mesh=mesh, partitions=owned)

    def _ensure_segment(self, segment_path: str, spec) -> None:
        """Build the columnar segment if absent (covering EVERY partition — it
        is a shared artifact), else auto-extend it with the post-build delta.
        Blocking; callers run it in the executor. Shared by the segment
        restore and the query engine (both scan committed chunks)."""
        import os

        from surge_tpu.log.columnar import build_segment_from_topic

        evt_fmt = self.logic.event_format
        if not os.path.exists(segment_path):
            # build to a UNIQUE temp path and rename: a crash mid-build must
            # not leave a partial file later cold starts would silently
            # restore from, and two concurrent builders (queries racing the
            # first build) must never interleave writes into one tmp file —
            # each builds a complete segment and the atomic os.replace makes
            # the last one win whole (a duplicate build is wasted work, never
            # corruption)
            import glob
            import time as _time
            import uuid

            # sweep partials orphaned by a hard-killed builder (the unique
            # names never self-heal by overwrite); the age guard protects a
            # concurrent builder's live tmp file
            for stale in glob.glob(f"{segment_path}.building.*"):
                try:
                    if _time.time() - os.path.getmtime(stale) > 600:
                        os.unlink(stale)
                        logger.warning("removed stale segment build %s", stale)
                except OSError:
                    pass
            tmp_path = f"{segment_path}.building.{os.getpid()}.{uuid.uuid4().hex[:8]}"
            try:
                build_segment_from_topic(
                    self.log, self.logic.events_topic, spec.registry,
                    evt_fmt.read_event, tmp_path,
                    encode_event=getattr(self.logic, "encode_event", None),
                    derived_cols=getattr(self.logic, "derived_cols", None),
                    state_topic=self.logic.state_topic)
                os.replace(tmp_path, segment_path)
            finally:
                # a failed build's uniquely-named partial must not linger
                if os.path.exists(tmp_path):
                    try:
                        os.unlink(tmp_path)
                    except OSError:
                        pass
        elif self.config.get_bool("surge.replay.segment-auto-extend", True):
            # incremental maintenance: append delta chunks/snapshots for offsets
            # past the segment's watermarks so THIS restore (and the next one)
            # covers them without a state-topic crawl. Best-effort exclusive
            # lock — if another engine on a shared path is extending, skip; the
            # post-restore state window replay covers the delta anyway.
            from surge_tpu.log.columnar import extend_segment_from_topic

            lock_path = segment_path + ".extending"
            try:
                fd = os.open(lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                fd = None
                try:  # a crash mid-extend must not disable extension forever:
                    # reclaim locks older than 10 minutes (extends are fast —
                    # they cover only the post-build delta)
                    import time as _time

                    if _time.time() - os.path.getmtime(lock_path) > 600:
                        os.unlink(lock_path)
                        fd = os.open(lock_path,
                                     os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                        logger.warning("reclaimed stale segment-extend lock %s",
                                       lock_path)
                    else:
                        logger.info("segment extend skipped: %s held by a "
                                    "concurrent extender", lock_path)
                except OSError:
                    fd = None
            if fd is not None:
                try:
                    extend_segment_from_topic(
                        self.log, self.logic.events_topic, spec.registry,
                        evt_fmt.read_event, segment_path,
                        encode_event=getattr(self.logic, "encode_event", None),
                        state_topic=self.logic.state_topic)
                finally:
                    os.close(fd)
                    os.unlink(lock_path)

    # -- query engine (TPU scans over committed columnar segments) ----------------------

    @property
    def query_engine(self):
        """Lazily-built :class:`surge_tpu.replay.query.QueryEngine` for this
        family (mesh-aware: scans shard their event axis over the replay
        mesh). The analytics half of the KTable analogy — docs/replay.md
        "Query engine"."""
        eng = getattr(self, "_query_engine", None)
        if eng is None:
            from surge_tpu.replay.query import QueryEngine

            eng = self._query_engine = QueryEngine(
                self.logic.replay_spec(), config=self.config,
                mesh=self._resolve_mesh())
        return eng

    def _segment_path_for_query(self) -> str:
        path = self.config.get_str("surge.replay.segment-path", "")
        if not path:
            raise ValueError(
                "query requires surge.replay.segment-path (the committed "
                "columnar segment the scan engine reads)")
        return path

    async def query(self, query, partitions=None):
        """Run a :class:`~surge_tpu.replay.query.ScanQuery` (or its JSON dict
        form) over the committed columnar segment: predicate-pushdown filter +
        grouped aggregates keyed by aggregate id, batched (and mesh-sharded)
        on device. Builds/extends the segment first if needed; the whole scan
        runs in the executor — the event loop keeps serving commands."""
        from surge_tpu.replay.query import ScanQuery

        if isinstance(query, dict):
            query = ScanQuery.from_json(query)
        path = self._segment_path_for_query()
        spec = self.logic.replay_spec()
        loop = asyncio.get_running_loop()

        def run():
            self._ensure_segment(path, spec)
            return self.query_engine.scan_segment(
                path, query,
                partitions=set(partitions) if partitions is not None else None)

        result = await loop.run_in_executor(None, run)
        self._record_query(result, "scan")
        return result

    async def query_states(self, query, partitions=None):
        """Run a :class:`~surge_tpu.replay.query.StateQuery` (or its JSON dict
        form): fold the segment's chunks to current aggregate state through
        the (mesh-aware) replay engine, filter on state columns, project
        ``select``. The "every matching aggregate's current state" read the
        per-key store cannot answer without a full scan."""
        from surge_tpu.replay.query import StateQuery

        if isinstance(query, dict):
            query = StateQuery.from_json(query)
        path = self._segment_path_for_query()
        spec = self.logic.replay_spec()
        loop = asyncio.get_running_loop()

        def run():
            self._ensure_segment(path, spec)
            from surge_tpu.replay import ReplayEngine

            reng = getattr(self, "_query_replay_engine", None)
            if reng is None:
                reng = self._query_replay_engine = ReplayEngine(
                    spec, config=self.config, mesh=self._resolve_mesh())
            return self.query_engine.query_states_segment(
                path, query, reng,
                partitions=set(partitions) if partitions is not None else None)

        result = await loop.run_in_executor(None, run)
        self._record_query(result, "state")
        return result

    def _record_query(self, result, kind: str) -> None:
        """Query-engine observability off one scan result: the coarse
        timers plus the observatory's scan-rows / pushdown-selectivity
        instruments, the ledger's ``query`` event, and (traced) a
        retro-dated ``query.scan`` span whose device leg lets trace
        anatomy attribute a slow query to device dispatch."""
        m = self.metrics
        m.query_scan_timer.record_ms(result.elapsed_s * 1000.0)
        m.query_scanned_events.record(result.scanned_events)
        m.query_result_rows.record(result.num_aggregates)
        m.query_scan_rows.record(result.num_aggregates)
        m.query_pushdown_selectivity.record(
            result.matched_events / result.scanned_events
            if result.scanned_events else 0.0)
        self.replay_ledger.record_query(
            rows=result.num_aggregates, scanned=result.scanned_events,
            matched=result.matched_events,
            elapsed_us=result.elapsed_s * 1e6, kind=kind)
        if self.tracer is not None:
            span = self.tracer.start_span("query.scan")
            # retro-dated on BOTH clocks (the profiler span discipline):
            # the tail sampler and anatomy read the mono pair first
            span.start_time = time.time() - result.elapsed_s
            span.start_mono = time.monotonic() - result.elapsed_s
            try:
                span.set_attribute("kind", kind)
                span.set_attribute("leg.dispatch-ms",
                                   round(result.elapsed_s * 1000.0, 3))
                span.set_attribute("rows", result.num_aggregates)
                span.set_attribute("scanned", result.scanned_events)
            finally:
                span.finish()

    # -- materialized views + changefeeds (docs/replay.md) ------------------------------

    def _require_views(self):
        if self.views is None:
            raise RuntimeError(
                "materialized views need the resident plane "
                "(surge.replay.resident.enabled) — there is no refresh feed "
                "to fold them from without it")
        return self.views

    def register_view(self, view) -> None:
        """Register a :class:`~surge_tpu.replay.views.ViewDef` (or its JSON
        dict form). Before the plane's seed it joins the seed fold; on a
        running plane it parks pending and the plane backfills the committed
        prefix between refresh rounds."""
        from surge_tpu.replay.views import ViewDef

        self._require_views()
        if isinstance(view, dict):
            view = ViewDef.from_json(view)
        self.resident_plane.register_view(view)

    def unregister_view(self, name: str) -> bool:
        return self._require_views().unregister(name)

    async def query_view(self, name: str) -> dict:
        """Snapshot one materialized view: normalized columns over sorted
        keys (top-k cut applied), version + fold watermarks. Runs in the
        executor — a fold round may hold the views lock through a device
        scan, and the event loop must keep serving commands."""
        views = self._require_views()
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, views.snapshot, name)

    async def view_summary(self) -> list:
        """One operator row per registered view (the ``QueryView`` RPC's
        no-name form, ``chaos.py views`` and surgetop)."""
        views = self._require_views()
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, views.summary)

    async def subscribe_view(self, name: str, from_version=None):
        """Open a changefeed subscription (the ``SubscribeView`` RPC):
        yields per-round delta entries, starting with a reconciling snapshot
        unless ``from_version`` is a resume watermark the delta ring still
        covers. Close with ``engine.views.unsubscribe(sub)``."""
        views = self._require_views()
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: views.subscribe(name, from_version, loop=loop))


class EngineNotRunningError(Exception):
    """SurgeEngineNotRunningException analog (scaladsl/common)."""
