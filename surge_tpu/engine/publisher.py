"""Per-partition transactional publisher — the exactly-once write path.

Re-derivation of the protocol of the reference's ``KafkaProducerActorImpl``
(modules/command-engine/core/src/main/scala/surge/internal/kafka/
KafkaProducerActorImpl.scala:182-528) as an asyncio FSM:

- ``uninitialized`` → ``initializing``: open the transactional producer (fencing any
  zombie holding the same ``{prefix}-{state_topic}-{partition}`` id,
  KafkaProducerActorImpl.scala:124), commit a flush record to establish the epoch
  (:321-340), then
- ``waiting_for_ktable``: hold publishes until the state store has indexed everything
  already on the state topic (lag == 0, :341-376) so ``is_aggregate_state_current``
  answers are sound from the first command, then
- ``processing``: batch all pending publishes on a flush tick into ONE transaction
  spanning events + state topics (:397-453); on commit, acknowledge every batched
  publisher and track the published aggregates as **in-flight by state-topic offset**
  until the store's indexed watermark passes them (:580-699) — the gap that
  ``is_aggregate_state_current`` (:530-540) reports.
- Fencing (``ProducerFencedError``) fails the open batch, then either re-initializes
  (still partition owner: new epoch re-fences the impostor) or shuts down (ownership
  lost) — :502-528.
- Duplicate publish suppression by request id with a TTL (the ``PublishTracker``
  analog, :580-608) so an entity retrying a publish whose commit actually landed does
  not double-write.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Mapping, Optional, Protocol, Sequence

from surge_tpu.common import BackgroundTask, fail_future, logger, resolve_future
from surge_tpu.config import Config, default_config
from surge_tpu.log.transport import LogRecord, ProducerFencedError


class PublishFailedError(Exception):
    """The batch containing this publish could not be committed."""


class PublisherNotReadyError(Exception):
    """Publish attempted before initialization finished or after shutdown."""


class StoreProgress(Protocol):
    """The state store's indexing progress, as seen by the publisher (the KTable
    consumer-lag query, KafkaProducerActorImpl.scala:701-708)."""

    def indexed_watermark(self, topic: str, partition: int) -> int:
        """Offsets ``< watermark`` have been indexed into the materialized store."""


@dataclass
class _Pending:
    request_id: str
    aggregate_id: str
    records: List[LogRecord]
    future: "asyncio.Future[None]"


@dataclass
class PublisherStats:
    """Counters for tests/metrics (flush loop visibility)."""

    flushes: int = 0
    records_published: int = 0
    batches_failed: int = 0
    fences: int = 0
    reinitializations: int = 0
    dedup_hits: int = 0
    in_flight: int = 0


class PartitionPublisher:
    """Single-writer publisher for one (state-topic) partition."""

    def __init__(self, log, state_topic: str, events_topic: Optional[str],
                 partition: int, progress: StoreProgress,
                 config: Config | None = None, transactional_id_prefix: str = "surge",
                 still_owner: Callable[[], bool] = lambda: True,
                 on_signal: Callable[[str, str], None] | None = None,
                 metrics=None, tracer=None) -> None:
        self.log = log
        self.state_topic = state_topic
        self.events_topic = events_topic
        self.partition = partition
        self.progress = progress
        self.config = config or default_config()
        self.transactional_id = f"{transactional_id_prefix}-{state_topic}-{partition}"
        self.still_owner = still_owner
        self.on_signal = on_signal or (lambda name, level: None)

        self.state = "uninitialized"
        self.stats = PublisherStats()
        self.metrics = metrics  # EngineMetrics quiver (optional)
        self.tracer = tracer  # None = zero-overhead path
        self._producer = None
        self._pending: List[_Pending] = []
        self._in_flight: Dict[str, int] = {}  # aggregate_id -> max state offset published
        self._completed: Dict[str, float] = {}  # request_id -> completion time
        # request_id -> outcome future of the batch currently committing it; retries of
        # an in-flight request join the commit instead of re-queueing (exactly-once)
        self._committing: Dict[str, "asyncio.Future[Optional[Exception]]"] = {}
        self._watermark = 0
        self._ready = asyncio.Event()
        self._flush_interval = self.config.get_seconds("surge.producer.flush-interval-ms", 50)
        self._check_interval = self.config.get_seconds("surge.producer.ktable-check-interval-ms", 500)
        self._slow_txn_s = self.config.get_seconds("surge.producer.slow-transaction-warning-ms", 1000)
        self._dedup_ttl_s = self.config.get_seconds(
            "surge.producer.publish-dedup-ttl-ms", 60_000)
        self._single_record_opt_in = self.config.get_bool(
            "surge.feature-flags.experimental.disable-single-record-transactions")
        # surge.producer.enable-transactions=false: append every record individually
        # (no atomicity across events+state; still epoch-fenced) — the reference's
        # non-transactional producer mode for throughput-over-consistency setups
        self._transactions_enabled = self.config.get_bool(
            "surge.producer.enable-transactions", True)
        # non-transactional mode: request_id -> LogRecords already appended (with
        # offsets). A mid-batch failure keeps every affected request's appended
        # records here so a same-request_id retry resumes after them AND can still
        # contribute the full record list to the success bookkeeping — without this,
        # retries would either re-append (duplicating events on the log) or hand the
        # offset-alignment loop a short `committed` list.
        self._partial_records: Dict[str, List[LogRecord]] = {}
        self._partial_touched: Dict[str, float] = {}  # request_id -> last retry time
        # transactional mode: a commit whose OUTCOME IS UNKNOWN (transport
        # died, fencing mid-flight) keeps its batch here and retries it
        # VERBATIM under the same txn_seq — the broker's (now
        # restart-durable) dedup then answers a commit that actually landed,
        # instead of a re-batched different payload being appended beside it.
        # Kafka's producer retries fixed batches for exactly this reason.
        self._retry_batch: Optional[List[_Pending]] = None
        self._retry_attempts = 0
        self._retry_max = self.config.get_int(
            "surge.producer.publish-retry-max", 8)
        self._flush_task = BackgroundTask(self._flush_loop, f"publisher-flush-{partition}")
        self._progress_task = BackgroundTask(self._progress_loop, f"publisher-progress-{partition}")

    # -- lifecycle ----------------------------------------------------------------------

    async def start(self) -> None:
        self.state = "initializing"
        try:
            await self._initialize()
        except Exception as exc:
            # surface init failure to queued publishers instead of letting them ride
            # the timeout ladder with no root cause
            self.state = "failed"
            self.on_signal("surge.producer.init-failed", "error")
            for p in self._pending:
                fail_future(p.future, PublisherNotReadyError(f"init failed: {exc}"))
            self._pending.clear()
            raise
        self._flush_task.start()
        self._progress_task.start()

    async def stop(self) -> None:
        self.state = "stopped"
        self._ready.clear()
        await self._flush_task.stop()
        await self._progress_task.stop()
        for p in self._pending:
            fail_future(p.future, PublisherNotReadyError("publisher stopped"))
        self._pending.clear()
        if self._retry_batch is not None:
            for p in self._retry_batch:
                fail_future(p.future,
                            PublisherNotReadyError("publisher stopped"))
            self._retry_batch = None
            self._retry_attempts = 0

    async def _initialize(self) -> None:
        """Open producer (fences zombies), commit the flush record, gate on store lag."""
        self._producer = self.log.transactional_producer(self.transactional_id)
        self._producer.begin()
        self._producer.send(LogRecord(topic=self.state_topic, key=None, value=b"",
                                      partition=self.partition,
                                      headers={"surge-flush": "1"}))
        # unsequenced when the transport supports it: the epoch marker's
        # duplicates are harmless, and it must not consume the broker's
        # one-shot reopen-absorption window that a stashed
        # landed-but-unacked batch needs after a broker restart
        commit = getattr(self._producer, "commit_unsequenced",
                         self._producer.commit)
        commit()
        self.state = "waiting_for_ktable"
        while True:
            end = self.log.end_offset(self.state_topic, self.partition)
            self._watermark = self.progress.indexed_watermark(self.state_topic, self.partition)
            if self._watermark >= end:
                break
            await asyncio.sleep(self._check_interval)
        self.state = "processing"
        self._ready.set()

    async def wait_ready(self, timeout: float = 30.0) -> None:
        await asyncio.wait_for(self._ready.wait(), timeout)

    # -- publish path -------------------------------------------------------------------

    async def publish(self, aggregate_id: str, records: Sequence[LogRecord],
                      request_id: str,
                      headers: Optional[Mapping[str, str]] = None) -> None:
        """Queue records for the next flush transaction; resolves at commit.

        Raises :class:`PublishFailedError` if the batch fails — callers (the aggregate
        entity's persistence ladder, KTablePersistenceSupport.scala:71-156) retry with
        the SAME ``request_id`` so a commit that actually landed is not repeated.

        ``headers`` may carry a W3C trace context: the publish span (queue →
        commit ack, the hop the reference wraps around its producer publish)
        then chains under the caller's entity span.
        """
        if self.tracer is None:
            return await self._publish_inner(aggregate_id, records, request_id)
        span = self.tracer.start_span("publisher.publish",
                                      headers=headers or {})
        span.set_attribute("aggregate_id", aggregate_id)
        span.set_attribute("partition", self.partition)
        span.set_attribute("records", len(records))
        with span:  # records exceptions + finishes
            return await self._publish_inner(aggregate_id, records, request_id)

    async def _publish_inner(self, aggregate_id: str,
                             records: Sequence[LogRecord],
                             request_id: str) -> None:
        if self.state not in ("processing", "waiting_for_ktable", "initializing"):
            raise PublisherNotReadyError(f"publisher state={self.state}")
        if request_id in self._completed:
            self.stats.dedup_hits += 1
            return
        if self._retry_batch is not None:
            for sp in self._retry_batch:
                if sp.request_id == request_id:
                    # this request rides the in-limbo batch: join its outcome
                    self.stats.dedup_hits += 1
                    await asyncio.shield(sp.future)
                    return
        committing = self._committing.get(request_id)
        if committing is not None:
            # this request's batch is mid-commit (the caller timed out and retried
            # while the transaction was in flight): join the outcome, never re-queue
            self.stats.dedup_hits += 1
            outcome = await asyncio.shield(committing)
            if outcome is not None:
                raise PublishFailedError(str(outcome))
            return
        fut: "asyncio.Future[None]" = asyncio.get_running_loop().create_future()
        pending = _Pending(request_id, aggregate_id, list(records), fut)
        self._pending.append(pending)
        try:
            await fut
        except asyncio.CancelledError:
            # caller timed out: withdraw the queued write so a same-request_id retry
            # does not double-queue it. If the flush already drained it, the commit may
            # still land — then the retry is absorbed by the _completed dedup.
            try:
                self._pending.remove(pending)
            except ValueError:
                pass
            raise

    def is_aggregate_state_current(self, aggregate_id: str) -> bool:
        """True iff nothing published for this aggregate is still ahead of the store's
        indexed watermark and nothing is pending (KafkaProducerActorImpl.scala:530-540)."""
        if any(p.aggregate_id == aggregate_id for p in self._pending):
            return False
        if self._retry_batch is not None and any(
                p.aggregate_id == aggregate_id for p in self._retry_batch):
            return False  # an in-limbo write is ahead of the store by definition
        off = self._in_flight.get(aggregate_id)
        if off is None:
            return True
        return off < self._watermark

    # -- internal loops -----------------------------------------------------------------

    async def _flush_loop(self) -> None:
        # the loop must be unkillable by a bug: _publish_batch fails batches
        # on expected errors, but an escape here (e.g. from post-commit
        # bookkeeping) would end the task SILENTLY and every later command on
        # this partition would time out with no root cause — same hazard
        # class as the broker's replication worker
        while True:
            await asyncio.sleep(self._flush_interval)
            batch: List[_Pending] = []
            try:
                if self.state in ("fenced", "waiting_for_ktable"):
                    # a fencing-triggered re-init that RAISED mid-way (broker
                    # briefly unreachable — it may already have advanced state
                    # past "fenced" before the escape) left init incomplete:
                    # keep retrying on the tick instead of sitting
                    # dead-but-running forever. _handle_fenced also covers
                    # the lost-ownership shutdown path.
                    await self._handle_fenced()
                if (self._retry_batch is not None
                        and self.state == "processing"):
                    # in-limbo batch retries VERBATIM before any new pendings
                    # commit (same txn_seq -> the broker dedup can answer it)
                    await self._publish_batch(self._retry_batch)
                elif self._pending and self.state == "processing":
                    batch, self._pending = self._pending, []
                    await self._publish_batch(batch)
                self._purge_dedup()
            except Exception as exc:  # noqa: BLE001 — log loudly, keep flushing
                logger.exception("flush loop iteration failed on %s[%d]; "
                                 "continuing", self.state_topic, self.partition)
                # the drained batch's waiters must not hang forever: fail
                # them so the entity ladder retries with the same request_id.
                # (If the commit actually landed before the escape, the
                # broker's txn_seq cache absorbs the replay while the broker
                # lives; across a broker RESTART that cache is rebuilt from
                # the __txn_state records it persists with each commit.)
                for p in batch:
                    fail_future(p.future, PublishFailedError(
                        f"flush loop error: {exc}"))
                try:
                    self.on_signal("surge.producer.flush-loop-error", "error")
                except Exception:  # noqa: BLE001 — a raising signal sink must
                    logger.exception("on_signal failed")  # not kill the loop

    async def _progress_loop(self) -> None:
        while True:
            try:
                self._refresh_watermark()
            except Exception:  # noqa: BLE001 — e.g. transient store lookup
                logger.exception("watermark refresh failed on %s[%d]; "
                                 "continuing", self.state_topic, self.partition)
            await asyncio.sleep(self._check_interval)

    def _refresh_watermark(self) -> None:
        self._watermark = self.progress.indexed_watermark(self.state_topic, self.partition)
        for agg_id in [a for a, off in self._in_flight.items() if off < self._watermark]:
            del self._in_flight[agg_id]
        self.stats.in_flight = len(self._in_flight)

    async def flush_now(self) -> None:
        """Immediate flush (test/shutdown hook; production path is the timed tick)."""
        if self._pending and self.state == "processing":
            batch, self._pending = self._pending, []
            await self._publish_batch(batch)

    async def _publish_batch(self, batch: List[_Pending]) -> None:
        records = [r for p in batch for r in p.records]
        outcome: "asyncio.Future[Optional[Exception]]" = \
            asyncio.get_running_loop().create_future()
        for p in batch:
            self._committing[p.request_id] = outcome
        # the flush-transaction span is a ROOT: one commit serves many pending
        # publishes, each already tracked by its own publisher.publish span
        span = None
        if self.tracer is not None:
            span = self.tracer.start_span("publisher.flush")
            span.set_attribute("partition", self.partition)
            span.set_attribute("batch_publishes", len(batch))
            span.set_attribute("batch_records", len(records))
        try:
            if span is None:
                await self._publish_batch_inner(batch, records, outcome)
            else:
                with span:
                    await self._publish_batch_inner(batch, records, outcome)
        finally:
            if not outcome.done():
                outcome.set_result(RuntimeError("publish batch aborted"))
            for p in batch:
                self._committing.pop(p.request_id, None)

    async def _publish_batch_inner(self, batch: List[_Pending],
                                   records: List[LogRecord],
                                   outcome: "asyncio.Future[Optional[Exception]]") -> None:
        t0 = time.perf_counter()
        try:
            if not self._transactions_enabled:
                # per-record appends: a mid-batch failure must not re-append any
                # already-written record on the entity's same-request_id retry, so
                # the appended records themselves are kept per request and retries
                # resume after them (contributing the full list to `committed` so
                # the offset-alignment loop below stays 1:1 with p.records)
                committed = []
                for p in batch:
                    done = self._partial_records.setdefault(p.request_id, [])
                    self._partial_touched[p.request_id] = time.time()
                    for i in range(len(done), len(p.records)):
                        done.append(self._producer.send_immediate(p.records[i]))
                    committed.extend(done)
                # every append landed: the batch is durable, drop the resume state
                for p in batch:
                    self._partial_records.pop(p.request_id, None)
                    self._partial_touched.pop(p.request_id, None)
            elif self._single_record_opt_in and len(records) == 1:
                committed = [self._producer.send_immediate(records[0])]
            else:
                self._producer.begin()
                for r in records:
                    self._producer.send(r)
                committed = list(self._producer.commit())
        except ProducerFencedError as exc:
            self.stats.fences += 1
            if self.metrics is not None:
                self.metrics.fence_counter.record()
            self.on_signal("surge.producer.fenced", "error")
            outcome.set_result(exc)
            if self._transactions_enabled:
                # outcome unknown (a failover ack may have landed): hold the
                # batch for a verbatim retry after re-init — the new broker's
                # replicated/durable dedup absorbs a landed commit
                self._stash_or_exhaust(batch, exc)
            else:
                for p in batch:
                    fail_future(p.future, PublishFailedError(
                        f"publisher for partition {self.partition} was fenced"))
            await self._handle_fenced()
            return
        except Exception as exc:  # noqa: BLE001 — transport failure: outcome unknown
            self.stats.batches_failed += 1
            if self.metrics is not None:
                self.metrics.publish_failure_counter.record()
            try:
                if getattr(self._producer, "in_transaction", False):
                    self._producer.abort()
            except Exception:  # noqa: BLE001
                self.on_signal("surge.producer.abort-failed", "error")
            outcome.set_result(exc)
            if self._transactions_enabled:
                self._stash_or_exhaust(batch, exc)
            else:
                # non-transactional mode has its own per-record resume state
                for p in batch:
                    fail_future(p.future, PublishFailedError(str(exc)))
            return

        elapsed = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.flush_timer.record_ms(elapsed * 1000.0)
        if elapsed > self._slow_txn_s:
            logger.warning("slow publish transaction: %.3fs on %s[%d]",
                           elapsed, self.state_topic, self.partition)
        # in-flight tracking: the max state-topic offset per aggregate in this commit
        by_index = iter(committed)
        now = time.time()
        for p in batch:
            max_state_off = None
            for _ in p.records:
                rec = next(by_index)
                if rec.topic == self.state_topic:
                    max_state_off = rec.offset if max_state_off is None else max(max_state_off, rec.offset)
            if max_state_off is not None:
                self._in_flight[p.aggregate_id] = max_state_off
            self._completed[p.request_id] = now
            resolve_future(p.future, None)
        outcome.set_result(None)
        if batch is self._retry_batch:
            self._retry_batch = None
            self._retry_attempts = 0
        self.stats.flushes += 1
        self.stats.records_published += len(records)
        self.stats.in_flight = len(self._in_flight)

    def _stash_or_exhaust(self, batch: List[_Pending], exc: Exception) -> None:
        """Keep an unknown-outcome batch for verbatim retry, bounded: after
        publish-retry-max attempts its waiters fail (the entity ladder takes
        over) and the batch is dropped — a deterministically-failing batch
        must not block the partition forever."""
        if self._retry_batch is None:
            self._retry_batch = batch
            self._retry_attempts = 1
        elif batch is not self._retry_batch:
            # a DIFFERENT batch failed while one is already in limbo (e.g. a
            # flush_now drain): only one verbatim-retry slot exists — fail the
            # newcomer's waiters so their entities retry, and leave the
            # in-limbo batch's accounting untouched
            for p in batch:
                fail_future(p.future, PublishFailedError(str(exc)))
            return
        else:
            self._retry_attempts += 1
        if self._retry_attempts > self._retry_max:
            logger.error(
                "publish batch on %s[%d] failed %d verbatim retries (%s); "
                "failing its waiters", self.state_topic, self.partition,
                self._retry_attempts, exc)
            for p in batch:
                fail_future(p.future, PublishFailedError(str(exc)))
            self._retry_batch = None
            self._retry_attempts = 0
        else:
            self.on_signal("surge.producer.publish-retry", "warning")

    async def _handle_fenced(self) -> None:
        """Fenced: re-init if we still own the partition, else shut down
        (KafkaProducerActorImpl.scala:502-528)."""
        self.state = "fenced"
        self._ready.clear()
        if self.still_owner():
            self.stats.reinitializations += 1
            self.on_signal("surge.producer.reinitializing", "warning")
            await self._initialize()
        else:
            self.on_signal("surge.producer.shutdown-not-owner", "warning")
            # runs inside the flush loop: mark stopped now, cancel the loops from a
            # separate task (a task cannot await its own cancellation)
            self.state = "stopped"
            asyncio.ensure_future(self.stop())

    def _purge_dedup(self) -> None:
        cutoff = time.time() - self._dedup_ttl_s
        for rid in [r for r, t in self._completed.items() if t < cutoff]:
            del self._completed[rid]
        # partial-resume state whose entity never retried again (crashed out of its
        # retry ladder) ages out on the same TTL
        for rid in [r for r, t in self._partial_touched.items() if t < cutoff]:
            self._partial_touched.pop(rid, None)
            self._partial_records.pop(rid, None)
