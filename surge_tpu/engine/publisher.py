"""Per-partition transactional publisher — the exactly-once write path.

Re-derivation of the protocol of the reference's ``KafkaProducerActorImpl``
(modules/command-engine/core/src/main/scala/surge/internal/kafka/
KafkaProducerActorImpl.scala:182-528) as an asyncio FSM:

- ``uninitialized`` → ``initializing``: open the transactional producer (fencing any
  zombie holding the same ``{prefix}-{state_topic}-{partition}`` id,
  KafkaProducerActorImpl.scala:124), commit a flush record to establish the epoch
  (:321-340), then
- ``waiting_for_ktable``: hold publishes until the state store has indexed everything
  already on the state topic (lag == 0, :341-376) so ``is_aggregate_state_current``
  answers are sound from the first command, then
- ``processing``: **event-driven group commit** (the Kafka producer's
  linger.ms/batch.size triggers replacing the fixed flush tick this file used
  to run): the first queued publish wakes the lane, the batch commits after
  ``surge.producer.linger-ms`` — or immediately once it hits
  ``batch-max-records``/``batch-max-bytes`` — as ONE transaction spanning
  events + state topics (:397-453). An idle lane therefore acks a lone
  command in ~linger time; a loaded lane fills batches. Commits run OFF the
  event loop (a dedicated lane thread, or pipelined transport futures), so
  the lanes of different partitions commit concurrently — the single-writer
  guarantee is per aggregate and aggregates hash to partitions, making
  cross-partition serialization pure overhead. Transports exposing
  ``commit_pipelined`` (the gRPC log client) additionally keep a bounded
  window of ``surge.producer.max-in-flight`` transactions in flight per lane,
  relying on the broker's replicated per-producer ``txn_seq`` dedup plus its
  in-order apply gate for exactly-once. On commit, acknowledge every batched
  publisher and track the published aggregates as **in-flight by state-topic
  offset** until the store's indexed watermark passes them (:580-699) — the
  gap that ``is_aggregate_state_current`` (:530-540) reports.
- Fencing (``ProducerFencedError``) fails the open batch, then either re-initializes
  (still partition owner: new epoch re-fences the impostor) or shuts down (ownership
  lost) — :502-528.
- Duplicate publish suppression by request id with a TTL (the ``PublishTracker``
  analog, :580-608) so an entity retrying a publish whose commit actually landed does
  not double-write.

Backpressure: publishes past ``surge.producer.pending-max-records`` queued
records await lane headroom instead of growing memory without bound under
overload; callers see added latency, never an unbounded queue.
"""

from __future__ import annotations

# surgelint: fast-path-module — the per-command publish lane (ISSUE 12)

import asyncio
import time
from collections import deque
from dataclasses import dataclass
from typing import (Callable, Deque, Dict, List, Mapping, Optional, Protocol,
                    Sequence)

from surge_tpu.common import (BackgroundTask, cancel_safe_wait_for,
                              fail_future, logger, resolve_future,
                              spawn_reaped)
from surge_tpu.config import Config, default_config
from surge_tpu.log.transport import (
    LogRecord,
    NotLeaderError,
    ProducerFencedError,
)


class PublishFailedError(Exception):
    """The batch containing this publish could not be committed."""


class PublisherNotReadyError(Exception):
    """Publish attempted before initialization finished or after shutdown."""


class StoreProgress(Protocol):
    """The state store's indexing progress, as seen by the publisher (the KTable
    consumer-lag query, KafkaProducerActorImpl.scala:701-708)."""

    def indexed_watermark(self, topic: str, partition: int) -> int:
        """Offsets ``< watermark`` have been indexed into the materialized store."""


@dataclass
class _Pending:
    request_id: str
    aggregate_id: str
    records: List[LogRecord]
    future: "asyncio.Future[None]"
    nbytes: int = 0
    #: the publish span's context captured at enqueue (tracer wired only):
    #: the flush span parents on the batch's FIRST pending's context, so a
    #: command's trace stays contiguous through the group commit down to the
    #: broker — including a timed-out caller whose same-request_id retry
    #: rejoins this queued write (the pending, and so the parenting, is the
    #: ORIGINAL publish's)
    trace_ctx: Optional[object] = None


class _Batch:
    """One group-commit unit: the pendings drained together, their flattened
    records, and (pipelined transports) the commit handle pinning the batch's
    txn_seq so an unknown-outcome batch retries VERBATIM under the same
    sequence number."""

    __slots__ = ("pendings", "records", "handle", "attempts", "index",
                 "dispatch_error", "outcome", "span")

    def __init__(self, pendings: List[_Pending], records: List[LogRecord],
                 index: int) -> None:
        self.pendings = pendings
        self.records = records
        self.handle = None
        self.attempts = 0
        self.index = index  # dispatch order: retries must replay oldest-first
        self.dispatch_error: Optional[Exception] = None
        #: the current attempt's flush span (opened at pipelined dispatch or
        #: by _publish_batch; cleared when the attempt's span finishes so a
        #: retry opens a fresh one in the same trace)
        self.span = None
        #: the current commit attempt's outcome (None = success, exception =
        #: why it failed); registered under _committing for every request id
        #: the moment the batch FORMS — a caller-timeout retry arriving while
        #: the commit task is still being scheduled must join, never re-queue
        self.outcome: Optional["asyncio.Future[Optional[Exception]]"] = None


class _Signal:
    """Level-triggered wakeup for ONE waiter (the flush loop), without
    ``wait_for(event.wait(), t)``: that wrapper costs a task per wait and —
    py3.10's wait_for — can swallow a cancellation racing its timeout,
    leaving the loop task uncancellable (the BackgroundTask.stop hang class
    fixed in surge_tpu.common). A bare awaited future cancels cleanly."""

    __slots__ = ("_set", "_waiter")

    def __init__(self) -> None:
        self._set = False
        self._waiter: Optional["asyncio.Future[None]"] = None

    def set(self) -> None:
        if not self._set:
            self._set = True
            w = self._waiter
            if w is not None and not w.done():
                w.set_result(None)

    def clear(self) -> None:
        self._set = False

    def is_set(self) -> bool:
        return self._set

    async def wait(self, timeout: float) -> bool:
        """True iff set (possibly before the timeout elapsed)."""
        if self._set:
            return True
        loop = asyncio.get_running_loop()
        w: "asyncio.Future[None]" = loop.create_future()
        self._waiter = w
        timer = loop.call_later(timeout, resolve_future, w, None)
        try:
            await w
        finally:
            timer.cancel()
            if self._waiter is w:
                self._waiter = None
        return self._set


@dataclass
class PublisherStats:
    """Counters for tests/metrics (flush loop visibility)."""

    flushes: int = 0
    records_published: int = 0
    batches_failed: int = 0
    fences: int = 0
    reinitializations: int = 0
    dedup_hits: int = 0
    in_flight: int = 0
    max_batch_records: int = 0
    inflight_peak: int = 0


class PartitionPublisher:
    """Single-writer publisher for one (state-topic) partition."""

    def __init__(self, log, state_topic: str, events_topic: Optional[str],
                 partition: int, progress: StoreProgress,
                 config: Config | None = None, transactional_id_prefix: str = "surge",
                 still_owner: Callable[[], bool] = lambda: True,
                 on_signal: Callable[[str, str], None] | None = None,
                 metrics=None, tracer=None, flight=None) -> None:
        self.log = log
        self.state_topic = state_topic
        self.events_topic = events_topic
        self.partition = partition
        self.progress = progress
        self.config = config or default_config()
        self.transactional_id = f"{transactional_id_prefix}-{state_topic}-{partition}"
        self.still_owner = still_owner
        self.on_signal = on_signal or (lambda name, level: None)

        self.state = "uninitialized"
        self.stats = PublisherStats()
        self.metrics = metrics  # EngineMetrics quiver (optional)
        self.tracer = tracer  # None = zero-overhead path
        #: engine flight recorder (optional): lane transitions — group-commit
        #: dispatch / verbatim retry / fence / rejoin — land in the same ring
        #: the broker events merge with on an incident timeline
        self.flight = flight
        self._producer = None
        self._pending: List[_Pending] = []
        self._in_flight: Dict[str, int] = {}  # aggregate_id -> max state offset published
        self._completed: Dict[str, float] = {}  # request_id -> completion time
        # request_id -> outcome future of the batch currently committing it; retries of
        # an in-flight request join the commit instead of re-queueing (exactly-once)
        self._committing: Dict[str, "asyncio.Future[Optional[Exception]]"] = {}
        # aggregate_id -> live commit-batch refcount: a write mid-commit is
        # ahead of the store even though it sits in neither _pending nor
        # _in_flight yet — is_aggregate_state_current must see it
        self._committing_aggs: Dict[str, int] = {}
        self._watermark = 0
        self._ready = asyncio.Event()
        # housekeeping tick: fenced-reinit retries, verbatim-retry pacing,
        # dedup purges (the flush itself is event-driven; pre-group-commit
        # this interval WAS the fixed flush tick, so configs lowering it for
        # fast tests keep their meaning as the recovery cadence)
        self._flush_interval = self.config.get_seconds("surge.producer.flush-interval-ms", 50)
        # group-commit triggers: the legacy flush tick stays an upper bound on
        # linger so configs tuned for the old fixed tick never get slower
        self._linger_s = min(
            self.config.get_seconds("surge.producer.linger-ms", 2),
            self._flush_interval)
        self._batch_max_records = max(1, self.config.get_int(
            "surge.producer.batch-max-records", 512))
        self._batch_max_bytes = max(1, self.config.get_int(
            "surge.producer.batch-max-bytes", 4 << 20))
        self._pending_max = max(1, self.config.get_int(
            "surge.producer.pending-max-records", 16_384))
        self._max_in_flight = max(1, self.config.get_int(
            "surge.producer.max-in-flight", 4))
        self._check_interval = self.config.get_seconds("surge.producer.ktable-check-interval-ms", 500)
        self._slow_txn_s = self.config.get_seconds("surge.producer.slow-transaction-warning-ms", 1000)
        self._dedup_ttl_s = self.config.get_seconds(
            "surge.producer.publish-dedup-ttl-ms", 60_000)
        self._single_record_opt_in = self.config.get_bool(
            "surge.feature-flags.experimental.disable-single-record-transactions")
        # surge.producer.enable-transactions=false: append every record individually
        # (no atomicity across events+state; still epoch-fenced) — the reference's
        # non-transactional producer mode for throughput-over-consistency setups
        self._transactions_enabled = self.config.get_bool(
            "surge.producer.enable-transactions", True)
        # non-transactional mode: request_id -> LogRecords already appended (with
        # offsets). A mid-batch failure keeps every affected request's appended
        # records here so a same-request_id retry resumes after them AND can still
        # contribute the full record list to the success bookkeeping — without this,
        # retries would either re-append (duplicating events on the log) or hand the
        # offset-alignment loop a short `committed` list.
        self._partial_records: Dict[str, List[LogRecord]] = {}
        self._partial_touched: Dict[str, float] = {}  # request_id -> last retry time
        # transactional mode: commits whose OUTCOME IS UNKNOWN (transport
        # died, fencing mid-flight) keep their batches here — in dispatch
        # order — and retry them VERBATIM under the same txn_seq BEFORE any
        # new pendings commit; the broker's (restart-durable, replicated)
        # dedup then answers a commit that actually landed, instead of a
        # re-batched different payload being appended beside it. Kafka's
        # producer retries fixed batches for exactly this reason. A pipelined
        # window can strand up to max-in-flight batches at once.
        self._retry_batches: Deque[_Batch] = deque()
        self._retry_max = self.config.get_int(
            "surge.producer.publish-retry-max", 8)
        # command lane (ISSUE 12): "direct" = batch-level ack futures +
        # queued-request joins (no per-command future/withdraw machinery);
        # "classic" = the PR-3 per-command path (paired bench arm)
        self._direct = self.config.get_str(
            "surge.producer.command-lane", "direct") != "classic"
        #: entities consult this to pick the right timeout primitive: a
        #: shared ack must never be cancelled by one caller's timeout
        self.shared_acks = self._direct
        #: the forming batch's shared ack future (direct lane). Rotated at
        #: every batch-max-records boundary so a drained batch NEVER shares
        #: its ack with still-queued pendings (the one invariant batch-level
        #: resolution rests on; _take_batch splits only at count boundaries)
        self._forming_ack: Optional["asyncio.Future[None]"] = None
        #: request_id -> queued pending's ack: a caller-timeout retry JOINS
        #: the queued write instead of double-queueing (direct lane's
        #: replacement for classic's cancel-withdraw callback)
        self._queued_rids: Dict[str, "asyncio.Future[None]"] = {}
        # flush machinery: _wake = a pending exists, _batch_full = a size/bytes
        # trigger fired, _pending_room = backpressure gate (multi-waiter,
        # rare path — a plain Event is fine there)
        self._wake = _Signal()
        self._batch_full = _Signal()
        self._self_stops: set = set()  # not-owner teardown tasks (reaped)
        self._pending_room = asyncio.Event()
        self._pending_room.set()
        self._pending_bytes = 0
        self._first_pending_t: Optional[float] = None
        self._batch_counter = 0
        self._inflight = 0
        self._slots = asyncio.Semaphore(self._max_in_flight)
        self._commit_tasks: set = set()
        self._lane_pool = None  # single-thread commit lane (lazy; off-loop fsync)
        self._flush_task = BackgroundTask(self._flush_loop, f"publisher-flush-{partition}")
        self._progress_task = BackgroundTask(self._progress_loop, f"publisher-progress-{partition}")

    # -- lifecycle ----------------------------------------------------------------------

    async def start(self) -> None:
        self.state = "initializing"
        try:
            await self._initialize()
        except Exception as exc:
            # surface init failure to queued publishers instead of letting them ride
            # the timeout ladder with no root cause
            self.state = "failed"
            self.on_signal("surge.producer.init-failed", "error")
            for p in self._pending:
                fail_future(p.future, PublisherNotReadyError(f"init failed: {exc}"))
            self._pending.clear()
            self._queued_rids.clear()
            self._forming_ack = None
            raise
        # pipelining depth: transports without pipelined commits (in-process
        # logs) run ONE commit in flight per lane — the commit's own latency
        # then paces the group commit, growing batches under load instead of
        # queueing linger-sized ones behind the lane thread
        depth = self._max_in_flight if self._pipeline_capable() else 1
        self._slots = asyncio.Semaphore(depth)
        self._flush_task.start()
        self._progress_task.start()

    async def stop(self) -> None:
        self.state = "stopped"
        self._ready.clear()
        self._pending_room.set()  # release backpressure waiters to the state check
        await self._flush_task.stop()
        await self._progress_task.stop()
        if self._commit_tasks:
            # let in-flight commits resolve their waiters; cancel stragglers
            done, still = await asyncio.wait(list(self._commit_tasks),
                                             timeout=5.0)
            for t in still:
                t.cancel()
            if still:
                await asyncio.gather(*still, return_exceptions=True)
        for p in self._pending:
            fail_future(p.future, PublisherNotReadyError("publisher stopped"))
        self._pending.clear()
        self._pending_bytes = 0
        self._queued_rids.clear()
        self._forming_ack = None
        while self._retry_batches:
            batch = self._retry_batches.popleft()
            for p in batch.pendings:
                fail_future(p.future,
                            PublisherNotReadyError("publisher stopped"))
        self._committing.clear()
        self._committing_aggs.clear()
        if self._lane_pool is not None:
            self._lane_pool.shutdown(wait=False)
            self._lane_pool = None

    async def _initialize(self) -> None:
        """Open producer (fences zombies), commit the flush record, gate on store lag."""
        self._producer = self.log.transactional_producer(self.transactional_id)
        self._producer.begin()
        self._producer.send(LogRecord(topic=self.state_topic, key=None, value=b"",
                                      partition=self.partition,
                                      headers={"surge-flush": "1"}))
        # unsequenced when the transport supports it: the epoch marker's
        # duplicates are harmless, and it must not consume the broker's
        # one-shot reopen-absorption window that a stashed
        # landed-but-unacked batch needs after a broker restart
        commit = getattr(self._producer, "commit_unsequenced",
                         self._producer.commit)
        commit()
        self.state = "waiting_for_ktable"
        while True:
            end = self.log.end_offset(self.state_topic, self.partition)
            self._watermark = self.progress.indexed_watermark(self.state_topic, self.partition)
            if self._watermark >= end:
                break
            await asyncio.sleep(self._check_interval)
        self.state = "processing"
        self._ready.set()

    async def wait_ready(self, timeout: float = 30.0) -> None:
        # cancel-safe (and the fast-path lint's sanctioned coroutine wait)
        await cancel_safe_wait_for(self._ready.wait(), timeout)

    # -- publish path -------------------------------------------------------------------

    def publish(self, aggregate_id: str, records: Sequence[LogRecord],
                request_id: str,
                headers: Optional[Mapping[str, str]] = None):
        """Queue records for the next group commit; the returned awaitable
        resolves at commit. The hot path returns a BARE FUTURE (no coroutine,
        so the entity's ``asyncio.wait_for`` needs no wrapper task — a real
        per-command cost at engine throughput); dedup joins, backpressure and
        the traced path return a coroutine.

        Raises :class:`PublishFailedError` if the batch fails — callers (the aggregate
        entity's persistence ladder, KTablePersistenceSupport.scala:71-156) retry with
        the SAME ``request_id`` so a commit that actually landed is not repeated.

        ``headers`` may carry a W3C trace context: the publish span (queue →
        commit ack, the hop the reference wraps around its producer publish)
        then chains under the caller's entity span.
        """
        if self.tracer is None:
            if (self.state == "processing"
                    and request_id not in self._completed
                    and not self._retry_batches
                    and request_id not in self._committing
                    and len(self._pending) < self._pending_max):
                if self._direct:
                    ack = self._queued_rids.get(request_id)
                    if ack is not None:
                        # caller-timeout retry while the original is still
                        # queued: join the queued write, never double-queue
                        self.stats.dedup_hits += 1
                        if ack.cancelled():
                            ack = self._refresh_cancelled_ack(ack)
                        return ack
                return self._queue_pending(aggregate_id, records, request_id)
            return self._publish_slow(aggregate_id, records, request_id)
        return self._publish_traced(aggregate_id, records, request_id, headers)

    async def _publish_traced(self, aggregate_id: str,
                              records: Sequence[LogRecord], request_id: str,
                              headers: Optional[Mapping[str, str]]) -> None:
        span = self.tracer.start_span("publisher.publish",
                                      headers=headers or {})
        span.set_attribute("aggregate_id", aggregate_id)
        span.set_attribute("partition", self.partition)
        span.set_attribute("records", len(records))
        with span:  # records exceptions + finishes
            return await self._publish_slow(aggregate_id, records, request_id)

    def _queue_pending(self, aggregate_id: str, records: Sequence[LogRecord],
                       request_id: str) -> "asyncio.Future[None]":
        """Hot path: enqueue for the next group commit, return the ack future.

        Direct lane: every pending of the forming batch shares ONE ack
        future, resolved once at commit — no per-command future creation,
        no withdraw callback (a timed-out caller's records stay queued; its
        same-request_id retry joins via ``_queued_rids``). The ack rotates
        at each batch-max-records boundary so a drained batch never shares
        its ack with still-queued pendings."""
        nbytes = 0
        for r in records:
            nbytes += ((len(r.value) if r.value else 0)
                       + (len(r.key) if r.key else 0) + 24)
        trace_ctx = None
        if self.tracer is not None:
            # the caller's publish span (active: _publish_traced queues from
            # inside `with span:`): the flush span parents on it, keeping
            # the command's trace contiguous down to the broker
            from surge_tpu.tracing import active_span

            span = active_span()
            if span is not None and span.context.sampled:
                trace_ctx = span.context
        if self._direct:
            fut = self._forming_ack
            if fut is None or fut.done():
                # done() covers a caller having cancelled the shared ack
                # outright: new publishes must never ride a dead future
                fut = self._forming_ack = \
                    asyncio.get_running_loop().create_future()
            pending = _Pending(request_id, aggregate_id, list(records), fut,
                               nbytes, trace_ctx=trace_ctx)
            self._pending.append(pending)
            self._queued_rids[request_id] = fut
            self._pending_bytes += nbytes
            if self._first_pending_t is None:
                self._first_pending_t = time.monotonic()
            self._wake.set()
            if len(self._pending) % self._batch_max_records == 0:
                self._forming_ack = None  # next pending opens a new batch ack
            if (len(self._pending) >= self._batch_max_records
                    or self._pending_bytes >= self._batch_max_bytes):
                self._batch_full.set()
            return fut
        fut = asyncio.get_running_loop().create_future()
        pending = _Pending(request_id, aggregate_id, list(records), fut,
                           nbytes, trace_ctx=trace_ctx)
        self._pending.append(pending)
        self._pending_bytes += nbytes
        if self._first_pending_t is None:
            self._first_pending_t = time.monotonic()
        self._wake.set()
        if (len(self._pending) >= self._batch_max_records
                or self._pending_bytes >= self._batch_max_bytes):
            self._batch_full.set()
        # caller timed out (future cancelled): withdraw the queued write so a
        # same-request_id retry does not double-queue it. If the flush already
        # drained it, the commit may still land — then the retry is absorbed
        # by the _completed dedup (or joins the in-flight commit / in-limbo
        # batch).
        fut.add_done_callback(lambda f: self._withdraw(pending)
                              if f.cancelled() else None)
        return fut

    @staticmethod
    async def _join_shared(fut: "asyncio.Future[None]") -> None:
        """Join a possibly-SHARED future shielded from this caller's
        cancellation, with the wait_future(owned=False) contract: a
        co-holder cancelling the shared future surfaces as a retryable
        PublishFailedError (the queued records still commit; the retry
        ladder rejoins by request id), never as CancelledError — while a
        REAL outer cancellation (which leaves the shared future pending)
        re-raises untouched."""
        try:
            await asyncio.shield(fut)
        except asyncio.CancelledError:
            if fut.cancelled():
                raise PublishFailedError(
                    "shared batch ack cancelled by another holder; retry")
            raise

    def _refresh_cancelled_ack(self, old: "asyncio.Future[None]"
                               ) -> "asyncio.Future[None]":
        """A caller cancelled a shared batch ack directly (the classic
        cancel-to-withdraw reflex; the direct lane's own timeout never
        cancels). The queued records still commit — swap in a fresh future
        for every pending riding the cancelled one so rejoining retries see
        the batch's real outcome, not the stale cancellation."""
        fresh: "asyncio.Future[None]" = \
            asyncio.get_running_loop().create_future()
        for p in self._pending:
            if p.future is old:
                p.future = fresh
        for rid, f in self._queued_rids.items():
            if f is old:
                self._queued_rids[rid] = fresh
        if self._forming_ack is old:
            self._forming_ack = fresh
        return fresh

    def _withdraw(self, pending: _Pending) -> None:
        try:
            self._pending.remove(pending)
            self._pending_bytes = max(0, self._pending_bytes - pending.nbytes)
        except ValueError:
            pass

    async def _publish_slow(self, aggregate_id: str,
                            records: Sequence[LogRecord],
                            request_id: str) -> None:
        if self.state not in ("processing", "waiting_for_ktable", "initializing"):
            raise PublisherNotReadyError(f"publisher state={self.state}")
        if request_id in self._completed:
            self.stats.dedup_hits += 1
            return
        if self._direct:
            ack = self._queued_rids.get(request_id)
            if ack is not None:
                # retry of a still-queued request (caller timed out before
                # the batch formed): join the queued write's batch ack
                self.stats.dedup_hits += 1
                if ack.cancelled():
                    ack = self._refresh_cancelled_ack(ack)
                await self._join_shared(ack)
                return
        for rb in self._retry_batches:
            for sp in rb.pendings:
                if sp.request_id == request_id:
                    # this request rides the in-limbo batch: join its outcome.
                    # If the original caller's timeout CANCELLED the waiter
                    # future, swap in a fresh one — the retry resolves
                    # whatever future the pending holds, and the rejoiner
                    # must see the batch's outcome, not the old cancellation.
                    self.stats.dedup_hits += 1
                    if sp.future.cancelled():
                        sp.future = asyncio.get_running_loop().create_future()  # surgelint: disable=hot-path-asyncio # rare rejoin slow path, not per-command
                    await self._join_shared(sp.future)  # surgelint: disable=hot-path-asyncio # rare rejoin slow path, not per-command
                    return
        committing = self._committing.get(request_id)
        if committing is not None:
            # this request's batch is mid-commit (the caller timed out and retried
            # while the transaction was in flight): join the outcome, never re-queue
            self.stats.dedup_hits += 1
            outcome = await asyncio.shield(committing)
            if outcome is not None:
                raise PublishFailedError(str(outcome))
            return
        # backpressure: overload queues no further than pending-max — the
        # caller waits for lane headroom (memory stays bounded; the entity's
        # publish timeout is the escape hatch if the lane never drains)
        while (len(self._pending) >= self._pending_max
               and self.state in ("processing", "waiting_for_ktable",
                                  "initializing")):
            self._pending_room.clear()
            await self._pending_room.wait()
        if self.state not in ("processing", "waiting_for_ktable", "initializing"):
            raise PublisherNotReadyError(f"publisher state={self.state}")
        ack = self._queue_pending(aggregate_id, records, request_id)
        if self._direct:
            # SHIELD the shared batch ack: this coroutine runs under the
            # entity's cancel-on-timeout wrapper, and a task cancellation
            # lands on the future it is parked on — unshielded, one caller's
            # timeout would cancel every sibling publish in the batch
            await asyncio.shield(ack)
        else:
            await ack

    def request_disposition(self, request_id: str) -> Optional[str]:
        """Where a request id sits in this publisher's dedup window:
        ``"completed"`` (committed inside the TTL window), ``"in-flight"``
        (queued / in-limbo / mid-commit), or None (never seen, or aged out).

        The entity consults this BEFORE running ``process_command`` for a
        caller-supplied request id (the saga manager's deterministic rids):
        a re-delivered command must short-circuit at the entity, because
        re-running the handler would fold its events into in-memory state a
        second time even though the publish itself dedups."""
        if request_id in self._completed:
            return "completed"
        if request_id in self._queued_rids or request_id in self._committing:
            return "in-flight"
        for rb in self._retry_batches:
            if any(sp.request_id == request_id for sp in rb.pendings):
                return "in-flight"
        if any(p.request_id == request_id for p in self._pending):
            return "in-flight"
        return None

    def is_aggregate_state_current(self, aggregate_id: str) -> bool:
        """True iff nothing published for this aggregate is still ahead of the store's
        indexed watermark and nothing is pending (KafkaProducerActorImpl.scala:530-540)."""
        if any(p.aggregate_id == aggregate_id for p in self._pending):
            return False
        if self._committing_aggs.get(aggregate_id):
            return False  # a commit is in flight for this aggregate right now
        for rb in self._retry_batches:
            if any(p.aggregate_id == aggregate_id for p in rb.pendings):
                return False  # an in-limbo write is ahead of the store by definition
        off = self._in_flight.get(aggregate_id)
        if off is None:
            return True
        return off < self._watermark

    # -- internal loops -----------------------------------------------------------------

    async def _flush_loop(self) -> None:
        # the loop must be unkillable by a bug: _publish_batch fails batches
        # on expected errors, but an escape here would end the task SILENTLY
        # and every later command on this partition would time out with no
        # root cause — same hazard class as the broker's replication worker
        while True:
            try:
                # wake-on-first-pending, or the housekeeping tick
                await self._wake.wait(self._flush_interval)
                if self.state in ("fenced", "waiting_for_ktable"):
                    # a fencing-triggered re-init that RAISED mid-way (broker
                    # briefly unreachable — it may already have advanced state
                    # past "fenced" before the escape) left init incomplete:
                    # keep retrying on the tick instead of sitting
                    # dead-but-running forever. _handle_fenced also covers
                    # the lost-ownership shutdown path.
                    await self._handle_fenced()
                if self._retry_batches and self.state == "processing":
                    # in-limbo batches retry VERBATIM, oldest dispatch first,
                    # before any new pendings commit (same txn_seq -> the
                    # broker dedup can answer a commit that landed); the
                    # pipeline drains first so the retry runs alone
                    await self._drain_inflight()
                    if self._retry_batches and self.state == "processing":
                        rb = self._retry_batches[0]
                        if self.flight is not None:
                            self.flight.record(
                                "lane.retry", partition=self.partition,
                                batch=rb.index, attempt=rb.attempts,
                                records=len(rb.records))
                        await self._publish_batch(rb)
                        if self._retry_batches and self._retry_batches[0] is rb:
                            # still failing: pace the next attempt on the tick
                            await asyncio.sleep(self._flush_interval)
                elif self._pending and self.state == "processing":
                    await self._await_linger()
                    if self.state == "processing":
                        batch = self._take_batch()
                        if batch is not None:
                            await self._dispatch(batch)
                self._purge_dedup()
            except Exception:  # noqa: BLE001 — log loudly, keep flushing
                logger.exception("flush loop iteration failed on %s[%d]; "
                                 "continuing", self.state_topic, self.partition)
                try:
                    self.on_signal("surge.producer.flush-loop-error", "error")
                except Exception:  # noqa: BLE001 — a raising signal sink must
                    logger.exception("on_signal failed")  # not kill the loop

    async def _await_linger(self) -> None:
        """Hold the batch open until linger elapses from the FIRST pending —
        or a size/bytes trigger fires first (wake-on-full)."""
        if self._linger_s <= 0 or self._first_pending_t is None:
            return
        deadline = self._first_pending_t + self._linger_s
        while not self._batch_full.is_set() and self.state == "processing":
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            if not await self._batch_full.wait(remaining):
                return

    def _take_batch(self) -> Optional[_Batch]:
        """Drain up to batch-max-records pendings into one commit unit."""
        if not self._pending:
            self._wake.clear()
            self._batch_full.clear()
            self._first_pending_t = None
            return None
        now = time.monotonic()
        formed_at = self._first_pending_t if self._first_pending_t is not None else now
        if len(self._pending) <= self._batch_max_records:
            pendings, self._pending = self._pending, []
            self._forming_ack = None  # the next pending opens a fresh ack
        else:
            pendings = self._pending[:self._batch_max_records]
            del self._pending[:self._batch_max_records]
            # leftovers keep their own ack(s): the rotation at every
            # batch-max boundary guarantees none of them share the drained
            # batch's future
        if self._direct:
            pop = self._queued_rids.pop
            for p in pendings:
                pop(p.request_id, None)
        self._pending_bytes = max(
            0, self._pending_bytes - sum(p.nbytes for p in pendings))
        self._pending_room.set()
        if self._pending:
            self._first_pending_t = now  # leftover pendings restart the linger
            if (len(self._pending) < self._batch_max_records
                    and self._pending_bytes < self._batch_max_bytes):
                self._batch_full.clear()
        else:
            self._wake.clear()
            self._batch_full.clear()
            self._first_pending_t = None
        records = [r for p in pendings for r in p.records]
        self._batch_counter += 1
        if self.metrics is not None:
            self.metrics.producer_linger_timer.record_ms((now - formed_at) * 1000.0)
            self.metrics.producer_lane_pending.record(len(self._pending))
        batch = _Batch(pendings, records, self._batch_counter)
        # register the mid-commit join point NOW (not when the commit task
        # first runs): between drain and task start a caller-timeout retry
        # must find its request in _committing, or it would double-queue
        batch.outcome = asyncio.get_running_loop().create_future()
        for p in pendings:
            self._committing[p.request_id] = batch.outcome
            self._committing_aggs[p.aggregate_id] = \
                self._committing_aggs.get(p.aggregate_id, 0) + 1
        return batch

    def _pipeline_capable(self) -> bool:
        return (self._transactions_enabled
                and not self._single_record_opt_in
                and self._producer is not None
                and hasattr(self._producer, "commit_pipelined"))

    def _open_flush_span(self, batch: _Batch):
        """One commit attempt's flush span, parented on the batch's first
        traced pending (module doc at _publish_batch); trace ids of the
        OTHER commands riding the same group commit go on ``trace.links``."""
        parent = next((p.trace_ctx for p in batch.pendings
                       if p.trace_ctx is not None), None)
        span = self.tracer.start_span("publisher.flush", parent=parent)
        span.set_attribute("partition", self.partition)
        span.set_attribute("batch_publishes", len(batch.pendings))
        span.set_attribute("batch_records", len(batch.records))
        if parent is not None:
            links = {p.trace_ctx.trace_id for p in batch.pendings
                     if p.trace_ctx is not None} - {parent.trace_id}
            if links:
                span.set_attribute("trace.links", sorted(links))
        return span

    def _start_pipelined(self, batch: _Batch) -> None:
        """Assign the batch's txn_seq and ship its Transact NOW (in dispatch
        order, on the loop) — the await happens in the commit task. A dispatch
        failure is recorded on the batch and surfaces through the shared
        commit-failure ladder."""
        try:
            if self.tracer is not None and batch.span is None:
                # opened BEFORE the Transact leaves (and activated around
                # the dispatch): the transport copies the calling context
                # into its pipeline pool, so the broker-call span — and the
                # broker-side span its traceparent seeds — chain under this
                # flush span instead of rooting fresh traces
                batch.span = self._open_flush_span(batch)
            if getattr(self._producer, "in_transaction", False):
                self._producer.abort()  # local buffer left by a failed dispatch
            # activate only if not already active: a re-dispatch from inside
            # _publish_batch's `with span:` block must not consume the with
            # block's activation token (deactivating the flush span for the
            # rest of the attempt — exemplars and child spans would detach)
            did_activate = (batch.span is not None
                            and batch.span._cv_token is None)
            if did_activate:
                batch.span.activate()
            try:
                self._producer.begin()
                for r in batch.records:
                    self._producer.send(r)
                batch.handle = self._producer.commit_pipelined()
            finally:
                if did_activate:
                    batch.span._deactivate()
        except Exception as exc:  # noqa: BLE001
            batch.dispatch_error = exc

    async def _dispatch(self, batch: _Batch) -> None:
        """Acquire an in-flight slot, ship the commit, return to batching."""
        await self._slots.acquire()
        self._inflight += 1
        if self._inflight > self.stats.inflight_peak:
            self.stats.inflight_peak = self._inflight
        if self.metrics is not None:
            self.metrics.producer_in_flight.record(self._inflight)
        if self.flight is not None:
            self.flight.record("lane.dispatch", partition=self.partition,
                               batch=batch.index, records=len(batch.records),
                               inflight=self._inflight)
        if self._pipeline_capable():
            self._start_pipelined(batch)
        task = asyncio.ensure_future(self._commit_task(batch))
        self._commit_tasks.add(task)
        task.add_done_callback(self._commit_tasks.discard)

    async def _commit_task(self, batch: _Batch) -> None:
        try:
            await self._publish_batch(batch)
        except asyncio.CancelledError:
            # publisher stopping: the drained batch's waiters must not hang
            for p in batch.pendings:
                fail_future(p.future,
                            PublisherNotReadyError("publisher stopped"))
            raise
        except Exception as exc:  # noqa: BLE001 — post-commit bookkeeping bug
            logger.exception("publish batch escaped on %s[%d]; failing its "
                             "waiters", self.state_topic, self.partition)
            # fail the waiters so the entity ladder retries with the same
            # request_id. (If the commit actually landed before the escape,
            # the broker's restart-durable txn_seq cache absorbs the replay.)
            for p in batch.pendings:
                fail_future(p.future, PublishFailedError(
                    f"publish batch error: {exc}"))
            try:
                self.on_signal("surge.producer.flush-loop-error", "error")
            except Exception:  # noqa: BLE001
                logger.exception("on_signal failed")
        finally:
            self._inflight -= 1
            if self.metrics is not None:
                self.metrics.producer_in_flight.record(self._inflight)
            self._slots.release()

    async def _drain_inflight(self) -> None:
        """Wait for every dispatched commit to resolve (retry/stop barrier)."""
        while self._commit_tasks:
            await asyncio.wait(list(self._commit_tasks))
            await asyncio.sleep(0)  # let done-callbacks run

    async def _progress_loop(self) -> None:
        while True:
            try:
                self._refresh_watermark()
            except Exception:  # noqa: BLE001 — e.g. transient store lookup
                logger.exception("watermark refresh failed on %s[%d]; "
                                 "continuing", self.state_topic, self.partition)
            await asyncio.sleep(self._check_interval)

    def _refresh_watermark(self) -> None:
        self._watermark = self.progress.indexed_watermark(self.state_topic, self.partition)
        for agg_id in [a for a, off in self._in_flight.items() if off < self._watermark]:
            del self._in_flight[agg_id]
        self.stats.in_flight = len(self._in_flight)

    async def flush_now(self) -> None:
        """Immediate flush (test/shutdown hook; production path is event-driven)."""
        while self._pending and self.state == "processing":
            await self._drain_inflight()
            batch = self._take_batch()
            if batch is None:
                return
            if self._pipeline_capable():
                self._start_pipelined(batch)
            await self._publish_batch(batch)

    async def _publish_batch(self, batch: _Batch) -> None:
        outcome = batch.outcome
        if outcome is None or outcome.done():
            # a RETRY attempt (the previous attempt resolved its outcome):
            # fresh join point under the same request ids. The aggregate
            # refcount stays as _take_batch counted it — the batch was never
            # terminal in between.
            outcome = asyncio.get_running_loop().create_future()
            batch.outcome = outcome
            for p in batch.pendings:
                self._committing[p.request_id] = outcome
        # the flush-transaction span parents on the batch's FIRST pending's
        # publish span (command anatomy, ISSUE 14): a single command's trace
        # is then contiguous ref → entity → publish → flush → broker call.
        # One commit still serves many pending publishes — the other
        # commands' trace ids ride the `trace.links` attribute (the OTel
        # span-link role), each already tracked by its own publish span.
        span = batch.span
        if span is None and self.tracer is not None:
            span = batch.span = self._open_flush_span(batch)
        try:
            if span is None:
                await self._publish_batch_inner(batch, outcome)
            else:
                with span:
                    await self._publish_batch_inner(batch, outcome)
        finally:
            batch.span = None  # a retry attempt opens a fresh flush span
            if not outcome.done():
                outcome.set_result(RuntimeError("publish batch aborted"))
            # unregister only when the batch is TERMINAL (committed, or its
            # waiters failed); a stashed in-limbo batch keeps its entries —
            # the slow path's retry-join runs BEFORE the committing-join, so
            # a rejoining request still lands on the verbatim retry
            if not any(b is batch for b in self._retry_batches):
                for p in batch.pendings:
                    self._committing.pop(p.request_id, None)
                    n = self._committing_aggs.get(p.aggregate_id, 0)
                    if n <= 1:
                        self._committing_aggs.pop(p.aggregate_id, None)
                    else:
                        self._committing_aggs[p.aggregate_id] = n - 1

    def _lane(self):
        """The lane's single commit thread: producer calls stay strictly
        ordered while fsync-heavy commits run OFF the event loop, letting
        other partitions' lanes (and the loop itself) proceed."""
        if self._lane_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._lane_pool = ThreadPoolExecutor(
                max_workers=1,
                thread_name_prefix=f"surge-commit-{self.partition}")
        return self._lane_pool

    def _commit_txn_blocking(self, batch: _Batch) -> List[LogRecord]:
        if getattr(self._producer, "in_transaction", False):
            self._producer.abort()  # buffer left open by a failed attempt
        self._producer.begin()
        for r in batch.records:
            self._producer.send(r)
        return list(self._producer.commit())

    def _commit_nontxn_blocking(self, batch: _Batch) -> List[LogRecord]:
        # per-record appends: a mid-batch failure must not re-append any
        # already-written record on the entity's same-request_id retry, so
        # the appended records themselves are kept per request and retries
        # resume after them (contributing the full list to `committed` so
        # the offset-alignment loop stays 1:1 with p.records)
        committed: List[LogRecord] = []
        for p in batch.pendings:
            done = self._partial_records.setdefault(p.request_id, [])
            self._partial_touched[p.request_id] = time.time()
            for i in range(len(done), len(p.records)):
                done.append(self._producer.send_immediate(p.records[i]))
            committed.extend(done)
        # every append landed: the batch is durable, drop the resume state
        for p in batch.pendings:
            self._partial_records.pop(p.request_id, None)
            self._partial_touched.pop(p.request_id, None)
        return committed

    async def _run_lane(self, fn, *args):
        """Run one blocking commit call on the lane thread. Traced
        publishers copy the calling context (the flush span above all) into
        the thread so the transport's broker-call span — read off
        ``active_span()`` over there — chains under the flush span instead
        of rooting a fresh trace; untraced publishers pay nothing."""
        loop = asyncio.get_running_loop()
        if self.tracer is None:
            return await loop.run_in_executor(self._lane(), fn, *args)
        import contextvars

        ctx = contextvars.copy_context()
        return await loop.run_in_executor(self._lane(), ctx.run, fn, *args)

    async def _commit_batch(self, batch: _Batch) -> List[LogRecord]:
        """Route one batch to its commit path; raises what the commit raised."""
        if batch.dispatch_error is not None:
            exc, batch.dispatch_error = batch.dispatch_error, None
            raise exc
        if not self._transactions_enabled:
            return await self._run_lane(self._commit_nontxn_blocking, batch)
        if self._single_record_opt_in and len(batch.records) == 1:
            return [await self._run_lane(
                self._producer.send_immediate, batch.records[0])]
        h = batch.handle
        if h is not None:
            if h.future.done() and (h.future.cancelled()
                                    or h.future.exception() is not None):
                # verbatim retry: same txn_seq on the same producer. A
                # producer re-opened since (new epoch after fencing) cannot
                # reuse the old token's seq — re-dispatch fresh below; the
                # broker's reopen absorption / numbering-past-pending-seqs
                # keeps a landed commit from doubling.
                if getattr(h, "producer", None) is self._producer:
                    self._producer.retry_pipelined(h)
                else:
                    batch.handle = None
                    return await self._commit_batch(batch)
            return await asyncio.wrap_future(batch.handle.future)
        if self._pipeline_capable():
            self._start_pipelined(batch)
            if batch.dispatch_error is not None:
                exc, batch.dispatch_error = batch.dispatch_error, None
                raise exc
            return await asyncio.wrap_future(batch.handle.future)
        return await self._run_lane(self._commit_txn_blocking, batch)

    async def _publish_batch_inner(self, batch: _Batch,
                                   outcome: "asyncio.Future[Optional[Exception]]") -> None:
        records = batch.records
        t0 = time.perf_counter()
        try:
            committed = await self._commit_batch(batch)
        except ProducerFencedError as exc:
            self.stats.fences += 1
            if self.metrics is not None:
                self.metrics.fence_counter.record()
            self.on_signal("surge.producer.fenced", "error")
            outcome.set_result(exc)
            if self._transactions_enabled:
                # outcome unknown (a failover ack may have landed): hold the
                # batch for a verbatim retry after re-init — the new broker's
                # replicated/durable dedup absorbs a landed commit
                self._stash_or_exhaust(batch, exc)
            else:
                for p in batch.pendings:
                    fail_future(p.future, PublishFailedError(
                        f"publisher for partition {self.partition} was fenced"))
            self._note_fenced()
            return
        except Exception as exc:  # noqa: BLE001 — transport failure: outcome unknown
            self.stats.batches_failed += 1
            if self.metrics is not None:
                self.metrics.publish_failure_counter.record()
            try:
                if getattr(self._producer, "in_transaction", False):
                    self._producer.abort()
            except Exception:  # noqa: BLE001
                self.on_signal("surge.producer.abort-failed", "error")
            outcome.set_result(exc)
            if self._transactions_enabled:
                self._stash_or_exhaust(batch, exc)
            else:
                # non-transactional mode has its own per-record resume state
                for p in batch.pendings:
                    fail_future(p.future, PublishFailedError(str(exc)))
            return

        elapsed = time.perf_counter() - t0
        if self.metrics is not None:
            self.metrics.flush_timer.record_ms(elapsed * 1000.0)
            self.metrics.producer_batch_records.record(len(records))
            self.metrics.producer_batch_commits.record()
        if len(records) > self.stats.max_batch_records:
            self.stats.max_batch_records = len(records)
        if elapsed > self._slow_txn_s:
            logger.warning("slow publish transaction: %.3fs on %s[%d]",
                           elapsed, self.state_topic, self.partition)
        # in-flight tracking: the max state-topic offset per aggregate in this commit
        by_index = iter(committed)
        now = time.time()
        for p in batch.pendings:
            max_state_off = None
            for _ in p.records:
                rec = next(by_index)
                if rec.topic == self.state_topic:
                    max_state_off = rec.offset if max_state_off is None else max(max_state_off, rec.offset)
            if max_state_off is not None:
                cur = self._in_flight.get(p.aggregate_id)
                if cur is None or max_state_off > cur:
                    self._in_flight[p.aggregate_id] = max_state_off
            self._completed[p.request_id] = now
            resolve_future(p.future, None)
        outcome.set_result(None)
        try:
            self._retry_batches.remove(batch)
        except ValueError:
            pass
        self.stats.flushes += 1
        self.stats.records_published += len(records)
        self.stats.in_flight = len(self._in_flight)

    def _note_fenced(self) -> None:
        """Mark the lane fenced; the flush loop's next tick runs the
        re-initialize-or-shutdown ladder (one reinit even when several
        pipelined commits observe the fence concurrently)."""
        if self.state == "processing":
            self.state = "fenced"
            self._ready.clear()
            if self.flight is not None:
                self.flight.record("lane.fence", partition=self.partition,
                                   fences=self.stats.fences)

    def _stash_or_exhaust(self, batch: _Batch, exc: Exception) -> None:
        """Keep an unknown-outcome batch for verbatim retry, bounded: after
        publish-retry-max attempts its waiters fail (the entity ladder takes
        over) and the batch is dropped — a deterministically-failing batch
        must not block the partition forever. Up to a pipelined window of
        batches can be in limbo at once; they retry in dispatch order."""
        if not any(b is batch for b in self._retry_batches):
            if len(self._retry_batches) >= self._max_in_flight + 1:
                # more limbo than the pipeline window can produce (e.g. a
                # flush_now drain during limbo): fail the newcomer's waiters
                # so their entities retry, leaving the window's accounting
                # untouched
                for p in batch.pendings:
                    fail_future(p.future, PublishFailedError(str(exc)))
                return
            batch.attempts = 1
            at = 0
            for i, b in enumerate(self._retry_batches):
                if b.index > batch.index:
                    break
                at = i + 1
            self._retry_batches.insert(at, batch)
        else:
            batch.attempts += 1
        if batch.attempts > self._retry_max:
            logger.error(
                "publish batch on %s[%d] failed %d verbatim retries (%s); "
                "failing its waiters", self.state_topic, self.partition,
                batch.attempts, exc)
            for p in batch.pendings:
                fail_future(p.future, PublishFailedError(str(exc)))
            try:
                self._retry_batches.remove(batch)
            except ValueError:
                pass
            if batch.handle is not None and getattr(batch.handle, "seq", 0):
                # the dropped batch CONSUMED a txn_seq at dispatch; abandoning
                # it would leave a permanent hole the broker's in-order gate
                # blocks every later seq behind. Force the lane through the
                # re-initialize ladder: the re-opened producer resumes its
                # numbering from the broker's acked/applied frontier, closing
                # the hole (and later in-limbo batches re-dispatch fresh on
                # the new producer).
                self._note_fenced()
        else:
            self.on_signal("surge.producer.publish-retry", "warning")

    async def _handle_fenced(self) -> None:
        """Fenced: re-init if we still own the partition, else shut down
        (KafkaProducerActorImpl.scala:502-528)."""
        self.state = "fenced"
        self._ready.clear()
        if self.still_owner():
            self.stats.reinitializations += 1
            self.on_signal("surge.producer.reinitializing", "warning")
            # partition-routed transports (surge_tpu.cluster.PartitionRouter)
            # cache this partition's leader: a fence very often MEANS the
            # leadership moved, so drop the cached hint before re-opening —
            # the fresh producer then resolves against the current map
            # instead of bouncing off the stale endpoint once more
            invalidate = getattr(self.log, "invalidate_partition", None)
            if invalidate is not None:
                try:
                    invalidate(self.state_topic, self.partition)
                except Exception:  # noqa: BLE001 — routing hint only
                    logger.exception("leader-hint invalidation failed")
            try:
                await self._initialize()
                if self.flight is not None:
                    self.flight.record(
                        "lane.rejoin", partition=self.partition,
                        reinitializations=self.stats.reinitializations)
            except NotLeaderError as exc:
                # the broker cluster is mid-failover (every reachable broker
                # is a follower; promotion has not landed yet): stay fenced
                # and retry on the housekeeping tick — a warning, not the
                # error-spam an exception escape would log
                self.state = "fenced"
                self.on_signal("surge.producer.waiting-for-leader", "warning")
                logger.warning(
                    "publisher %s[%d] waiting for a log leader: %s",
                    self.state_topic, self.partition, exc)
        else:
            self.on_signal("surge.producer.shutdown-not-owner", "warning")
            # runs inside the flush loop: mark stopped now, cancel the loops from a
            # separate task (a task cannot await its own cancellation);
            # retained + reaped so the teardown can't be GC'd mid-stop and a
            # failing stop logs instead of rotting
            self.state = "stopped"
            spawn_reaped(self._self_stops, self.stop(),
                         f"publisher {self.state_topic}[{self.partition}] "
                         "not-owner self-stop")

    def _purge_dedup(self) -> None:
        cutoff = time.time() - self._dedup_ttl_s
        for rid in [r for r, t in self._completed.items() if t < cutoff]:
            del self._completed[rid]
        # partial-resume state whose entity never retried again (crashed out of its
        # retry ladder) ages out on the same TTL
        for rid in [r for r, t in self._partial_touched.items() if t < cutoff]:
            self._partial_touched.pop(rid, None)
            self._partial_records.pop(rid, None)
