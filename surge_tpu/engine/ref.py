"""AggregateRef — the client proxy for one aggregate id.

Reference: internal/persistence/AggregateRefTrait.scala:31-102 + the scaladsl surface
(scaladsl/command/AggregateRef.scala:15-60): ``send_command`` / ``get_state`` /
``apply_events`` as ask-style calls with timeout mapping into the result ADTs
(CommandSuccess / CommandRejected / CommandFailure)."""

from __future__ import annotations

# surgelint: fast-path-module — the per-command ask boundary (ISSUE 12)

import asyncio
from typing import Any, Callable, Optional, Sequence

from surge_tpu.common import wait_future
from surge_tpu.config import Config, TimeoutConfig, default_config
from surge_tpu.engine.entity import (
    REQUEST_ID_HEADER,
    ApplyEvents,
    CommandFailure,
    CommandRejected,
    CommandSuccess,
    Envelope,
    GetState,
    ProcessMessage,
)

# deliver(aggregate_id, envelope) — a Shard, or the partition router in front of many
DeliverFn = Callable[[str, Envelope], None]


class AggregateRef:
    """Typed handle on one aggregate (AggregateRefTrait.scala:31-102)."""

    def __init__(self, aggregate_id: str, deliver: DeliverFn,
                 config: Config | None = None,
                 headers_factory: Callable[[], dict] | None = None,
                 tracer=None) -> None:
        self.aggregate_id = aggregate_id
        self._deliver = deliver
        self._timeouts = TimeoutConfig.from_config(config or default_config())
        self._headers_factory = headers_factory or dict
        self._tracer = tracer

    async def _ask(self, message: Any,
                   extra_headers: Optional[dict] = None) -> Any:
        fut: "asyncio.Future[Any]" = asyncio.get_running_loop().create_future()
        headers = self._headers_factory()
        if extra_headers:
            headers.update(extra_headers)
        span = None
        if self._tracer is not None:
            # span at the ask boundary, trace context rides the envelope headers
            # (AggregateRefTrait.scala:77-79 + TracedMessage)
            from surge_tpu.tracing import inject_context

            span = self._tracer.start_span(
                f"aggregate-ref.{type(message).__name__}", headers=headers)
            span.set_attribute("aggregate_id", self.aggregate_id)
            headers = inject_context(span.context, headers)
        env = Envelope(message=message, reply=fut, headers=headers)
        try:
            self._deliver(self.aggregate_id, env)
            # slim timer wait on the exclusively-owned reply future: no
            # wrapper task / waiter per ask (a per-command cost at engine
            # throughput); timeout cancels the reply exactly like wait_for
            return await wait_future(fut, self._timeouts.ask_timeout_s)
        except asyncio.TimeoutError as exc:
            if span is not None:
                span.record_exception(exc)
            return CommandFailure(exc)
        except Exception as exc:  # noqa: BLE001 — routing failures surface as failures
            if span is not None:
                span.record_exception(exc)
            return CommandFailure(exc)
        finally:
            if span is not None:
                span.finish()

    async def send_command(self, command: Any, *,
                           request_id: Optional[str] = None):
        """→ CommandSuccess(new_state) | CommandRejected(reason) | CommandFailure(err)
        (AggregateRefTrait.sendCommand:76-93).

        ``request_id`` rides the envelope headers into the entity, which
        publishes under it instead of minting one — a retried send with the
        same id dedups exactly-once (the saga manager's contract)."""
        result = await self._ask(
            ProcessMessage(command),
            {REQUEST_ID_HEADER: request_id} if request_id is not None else None)
        if isinstance(result, (CommandSuccess, CommandRejected, CommandFailure)):
            return result
        return CommandFailure(TypeError(f"unexpected reply {result!r}"))

    async def get_state(self) -> Optional[Any]:
        """Current state, or None (queryState:62-64). Raises on ask failure."""
        result = await self._ask(GetState())
        if isinstance(result, CommandFailure):
            raise result.error
        return result

    async def apply_events(self, events: Sequence[Any]):
        """Fold externally-produced events; → CommandSuccess | CommandFailure
        (applyEvents:95-101)."""
        result = await self._ask(ApplyEvents(list(events)))
        if isinstance(result, (CommandSuccess, CommandFailure)):
            return result
        return CommandFailure(TypeError(f"unexpected reply {result!r}"))
