"""Partition router: aggregate id → partition → local shard or remote node.

Reference: KafkaPartitionShardRouterActor (modules/common/src/main/scala/surge/kafka/
KafkaPartitionShardRouterActor.scala:25-372) — routes by the producer's partitioner
(deliverMessage:205-222), follows :class:`PartitionTracker` updates (rebalance region
lifecycle, updatePartitionAssignments:114-142), creates local regions on demand
(newActorRegionForPartition:248-283), and supports DR-standby (defer region creation
until first delivery, :174-185,309-316). Remote partitions forward through a pluggable
``remote_deliver`` (the Akka-remoting ActorSelection analog — the control-plane
transport supplies it; SURVEY.md §5.8)."""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from surge_tpu.common import (Ack, Controllable, fail_future, logger,
                              spawn_reaped)
from surge_tpu.engine.entity import Envelope
from surge_tpu.engine.partition import (
    AssignmentChanges,
    HostPort,
    PartitionAssignments,
    PartitionTracker,
    partition_by_up_to_colon,
    partition_for_key,
)
# module-level, NOT inside deliver(): a per-message import statement costs a
# sys.modules lookup on every delivery even when tracing is active, and the
# tracer=None path must stay a single `is None` check
from surge_tpu.tracing import inject_context

# region_creator(partition) -> a Shard-like object (deliver(agg_id, env) + async stop())
RegionCreator = Callable[[int], object]
# remote_deliver(host, partition, aggregate_id, envelope) — cross-node forwarding
RemoteDeliver = Callable[[HostPort, int, str, Envelope], None]


class NoRouteError(Exception):
    """No assignment known for the key's partition and no buffering headroom."""


class RouterBase(Controllable):
    """Shared routing machinery: key→partition hashing, pending-buffering while the
    owner is unknown, local-vs-remote dispatch, lazy region creation, and the
    health/regions accessors. Backends differ only in how a partition's owner is
    resolved (``owner_of``) and what drives rebalances."""

    health_name = "router"

    def __init__(self, num_partitions: int, local_host: HostPort,
                 region_creator: RegionCreator,
                 partition_by: Callable[[str], str] = partition_by_up_to_colon,
                 remote_deliver: Optional[RemoteDeliver] = None,
                 pending_limit: int = 1000) -> None:
        self.num_partitions = num_partitions
        self.local_host = local_host
        self.region_creator = region_creator
        self.partition_by = partition_by
        self.remote_deliver = remote_deliver
        self.pending_limit = pending_limit
        # assigned by the engine after construction (None = zero-overhead path);
        # the routing hop's span mirrors KafkaPartitionShardRouterActor:216
        self.tracer = None
        self._regions: Dict[int, object] = {}
        self._region_stops: set = set()  # in-flight region teardowns (reaped)
        self._pending: Dict[int, List[Tuple[str, Envelope]]] = {}
        self._started = False

    # -- backend hook -------------------------------------------------------------------

    def owner_of(self, partition: int) -> Optional[HostPort]:
        raise NotImplementedError

    # -- routing ------------------------------------------------------------------------

    def partition_for(self, aggregate_id: str) -> int:
        return partition_for_key(self.partition_by(aggregate_id), self.num_partitions)

    def deliver(self, aggregate_id: str, env: Envelope) -> None:
        """deliverMessage:205-222 — resolve owner, local-or-remote dispatch."""
        span = None
        if self.tracer is not None:
            span = self.tracer.start_span(
                f"{self.health_name}.deliver", headers=env.headers)
            span.set_attribute("aggregate_id", aggregate_id)
            env.headers = inject_context(span.context, env.headers)
        try:
            partition = self.partition_for(aggregate_id)
            owner = self.owner_of(partition)
            if span is not None:
                span.set_attribute("partition", partition)
                span.set_attribute("owner", "" if owner is None else str(owner))
                span.set_attribute(
                    "remote", owner is not None and owner != self.local_host)
            if owner is None:
                buf = self._pending.setdefault(partition, [])
                if len(buf) >= self.pending_limit:
                    err = NoRouteError(
                        f"no owner for partition {partition} and buffer full")
                    if span is not None:
                        span.record_exception(err)
                    fail_future(env.reply, err)
                    return
                buf.append((aggregate_id, env))
                if span is not None:
                    span.add_event("buffered")
                return
            self._dispatch(owner, partition, aggregate_id, env)
        finally:
            if span is not None:
                span.finish()

    def _dispatch(self, owner: HostPort, partition: int, aggregate_id: str,
                  env: Envelope) -> None:
        if owner == self.local_host:
            self.deliver_local(partition, aggregate_id, env)
        elif self.remote_deliver is not None:
            self.remote_deliver(owner, partition, aggregate_id, env)
        else:
            fail_future(env.reply, NoRouteError(
                f"partition {partition} owned by {owner} and no remote transport"))

    def _create_region(self, partition: int):
        region = self.region_creator(partition)
        self._regions[partition] = region
        return region

    def deliver_local(self, partition: int, aggregate_id: str, env: Envelope) -> None:
        """Deliver into this node's region for ``partition`` WITHOUT re-resolving
        ownership. ``_dispatch`` uses this for locally-owned partitions; the
        node-transport server uses it for envelopes another node already addressed
        here — re-routing those through ``deliver`` could ping-pong unboundedly
        while two nodes' trackers disagree mid-rebalance. Regions materialize
        lazily (DR-standby defers creation to first message, :174-185; normal mode
        lazily materializes too if an assignment listener raced a delivery)."""
        region = self._regions.get(partition)
        if region is None:
            region = self._create_region(partition)
        region.deliver(aggregate_id, env)

    def _stop_region(self, partition: int, why: str) -> None:
        import asyncio

        region = self._regions.pop(partition, None)
        if region is not None:
            logger.info("%s: stopping %s region %d", self.health_name, why, partition)
            spawn_reaped(self._region_stops, region.stop(),
                         f"{self.health_name} region {partition} stop")

    def _drain_pending(self) -> None:
        """Dispatch buffered deliveries whose owner is now known."""
        for p in list(self._pending):
            owner = self.owner_of(p)
            if owner is None:
                continue
            for aggregate_id, env in self._pending.pop(p):
                self._dispatch(owner, p, aggregate_id, env)

    async def _shutdown_regions(self) -> None:
        for region in list(self._regions.values()):
            await region.stop()
        self._regions.clear()
        for buf in self._pending.values():
            for _, env in buf:
                fail_future(env.reply, NoRouteError(f"{self.health_name} stopped"))
        self._pending.clear()

    @property
    def local_partitions(self) -> List[int]:
        return sorted(self._regions)

    def regions(self):
        """Public (partition, region) accessor in partition order: lets health/metrics
        compose without reaching into router internals."""
        return sorted(self._regions.items())

    def health(self) -> dict:
        """Router health snapshot (getHealthCheck:353-366 analog)."""
        return {
            "name": self.health_name,
            "status": "up" if self._started else "down",
            "local_partitions": self.local_partitions,
            "pending": {p: len(b) for p, b in self._pending.items()},
        }


class SurgePartitionRouter(RouterBase):
    """Default backend: partition owners come straight from the tracker's consumer
    assignments."""

    def __init__(self, num_partitions: int, tracker: PartitionTracker,
                 local_host: HostPort, region_creator: RegionCreator,
                 partition_by: Callable[[str], str] = partition_by_up_to_colon,
                 remote_deliver: Optional[RemoteDeliver] = None,
                 dr_standby: bool = False, pending_limit: int = 1000) -> None:
        super().__init__(num_partitions, local_host, region_creator,
                         partition_by=partition_by, remote_deliver=remote_deliver,
                         pending_limit=pending_limit)
        self.tracker = tracker
        self.dr_standby = dr_standby

    def owner_of(self, partition: int) -> Optional[HostPort]:
        return self.tracker.assignments.partition_to_host().get(partition)

    # -- lifecycle ----------------------------------------------------------------------

    async def start(self) -> Ack:
        self._started = True
        self.tracker.register(self._on_assignments)
        return Ack()

    async def stop(self) -> Ack:
        self._started = False
        self.tracker.unregister(self._on_assignments)
        await self._shutdown_regions()
        return Ack()

    # -- rebalance ----------------------------------------------------------------------

    def _on_assignments(self, assignments: PartitionAssignments,
                        changes: AssignmentChanges) -> None:
        if not self._started:
            return
        # stop revoked local regions (PoisonPill analog, :298-307)
        for p in changes.revoked.get(self.local_host, []):
            self._stop_region(p, "revoked")
        # eagerly create added local regions unless DR-standby (:144-156)
        if not self.dr_standby:
            for p in changes.added.get(self.local_host, []):
                if p not in self._regions:
                    self._create_region(p)
        self._drain_pending()
