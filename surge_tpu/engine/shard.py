"""Entity parent for one partition — the Shard equivalent.

Reference: modules/common/src/main/scala/surge/internal/akka/cluster/Shard.scala:34-200 —
creates a child entity per aggregate id on demand (getOrCreateEntity:101-113), buffers
messages (bounded) while a child passivates (receivePassivate:165-180, buffer:115-123),
and restarts a child that stopped with messages waiting (entityTerminated:134-147).
Crashed children are recreated on the next delivery with their unprocessed mail
redelivered — the fresh entity re-initializes from the state store.
"""

from __future__ import annotations

# surgelint: fast-path-module — the per-command delivery hop (ISSUE 12)

from typing import Callable, Dict, List

from surge_tpu.common import fail_future, logger
from surge_tpu.engine.entity import AggregateEntity, Envelope
# module-level, NOT inside deliver(): a per-message import statement costs a
# sys.modules lookup on every delivery even when tracing is active, and the
# tracer=None path must stay a single `is None` check
from surge_tpu.tracing import inject_context

# factory(aggregate_id, on_passivate, on_stopped) -> started-or-startable entity
EntityFactory = Callable[..., AggregateEntity]


class BufferFullError(Exception):
    """Passivation buffer overflow (Shard.scala:115-123 drops with a warning)."""


class Shard:
    """Owns the live entities of one partition."""

    def __init__(self, name: str, entity_factory: EntityFactory,
                 buffer_limit: int = 1000, tracer=None) -> None:
        self.name = name
        self.entity_factory = entity_factory
        self.buffer_limit = buffer_limit
        self.tracer = tracer
        self._entities: Dict[str, AggregateEntity] = {}
        self._passivating: Dict[str, List[Envelope]] = {}

    # -- delivery -----------------------------------------------------------------------

    def deliver(self, aggregate_id: str, env: Envelope) -> None:
        span = None
        if self.tracer is not None:
            # the Shard hop's span (getOrCreateEntity + mailbox handoff);
            # context re-injected so the entity's receive span chains under it
            span = self.tracer.start_span("shard.deliver", headers=env.headers)
            span.set_attribute("aggregate_id", aggregate_id)
            span.set_attribute("shard", self.name)
            env.headers = inject_context(span.context, env.headers)
        try:
            if aggregate_id in self._passivating:
                buf = self._passivating[aggregate_id]
                if len(buf) >= self.buffer_limit:
                    err = BufferFullError(
                        f"{self.name}: passivation buffer full for {aggregate_id}")
                    if span is not None:
                        span.record_exception(err)
                    fail_future(env.reply, err)
                    return
                buf.append(env)
                if span is not None:
                    span.add_event("buffered-passivating")
                return
            self._get_or_create(aggregate_id).deliver(env)
        finally:
            if span is not None:
                span.finish()

    def _get_or_create(self, aggregate_id: str) -> AggregateEntity:
        entity = self._entities.get(aggregate_id)
        if entity is None or entity.state_name == "stopped":
            entity = self.entity_factory(
                aggregate_id, on_passivate=self._on_passivate,
                on_stopped=self._on_stopped)
            self._entities[aggregate_id] = entity
            entity.start()
        return entity

    @property
    def num_live_entities(self) -> int:
        return len(self._entities)

    def live_entity(self, aggregate_id: str) -> AggregateEntity | None:
        return self._entities.get(aggregate_id)

    # -- passivation protocol (entity callbacks, same event loop) ------------------------

    def _on_passivate(self, aggregate_id: str) -> None:
        self._passivating.setdefault(aggregate_id, [])

    def _on_stopped(self, aggregate_id: str, leftovers: List[Envelope],
                    crashed: bool) -> None:
        self._entities.pop(aggregate_id, None)
        pending = self._passivating.pop(aggregate_id, []) + list(leftovers)
        if crashed:
            logger.warning("%s: entity %s crashed; %d message(s) to redeliver",
                           self.name, aggregate_id, len(pending))
        for env in pending:  # restart-on-buffered (Shard.scala:134-147)
            self.deliver(aggregate_id, env)

    # -- lifecycle ----------------------------------------------------------------------

    async def stop(self) -> None:
        for entity in list(self._entities.values()):
            await entity.stop()  # surgelint: disable=hot-path-asyncio # shutdown path, not per-command
        self._entities.clear()
        for buf in self._passivating.values():
            for env in buf:
                fail_future(env.reply, RuntimeError(f"shard {self.name} stopped"))
        self._passivating.clear()
