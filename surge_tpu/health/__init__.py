"""Health signals, pattern matching, and restart supervision.

Equivalents of the reference health stack (SURVEY.md §5.3/§5.5):

- :class:`HealthSignal` + :class:`HealthSignalBus` — local pub/sub with a ring buffer
  of recent signals and an emit DSL
  (modules/common/src/main/scala/surge/internal/health/HealthSignalBus.scala:162-371).
- :class:`SlidingSignalWindow` — time-windowed signal buffer advancing on expiry or
  buffer threshold (HealthSignalWindowActor.scala:22-120 + WindowSlider.scala:11-37).
- Signal pattern matchers — name-equals / regex / repeating-within-window
  (surge/internal/health/matchers/*.scala).
- :class:`HealthSupervisor` — matches registered restart/shutdown patterns against the
  signal stream and drives each component's ``Controllable`` restart()/shutdown(), with
  a restart budget before escalating to shutdown
  (internal/health/supervisor/HealthSupervisorActor.scala:63-111). Emits
  ``health.component-restarted`` back onto the bus (the ComponentRestarted ack the
  reference spec asserts on, SurgeMessagePipelineSpec:150-253).
"""

from __future__ import annotations

import re
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, Dict, List, Optional, Pattern, Sequence

from surge_tpu.common import (Ack, CircularBuffer, Controllable, logger,
                              spawn_reaped)
from surge_tpu.config import Config, default_config

__all__ = [
    "HealthSignal",
    "HealthSignalBus",
    "HealthSupervisor",
    "NameEqualsMatcher",
    "RegexMatcher",
    "RepeatingSignalMatcher",
    "SlidingSignalWindow",
]


@dataclass(frozen=True)
class HealthSignal:
    """A named signal (surge.health.HealthSignal): error/warning/trace severity."""

    name: str
    level: str = "warning"  # "error" | "warning" | "trace"
    source: str = ""
    metadata: dict = field(default_factory=dict)
    timestamp: float = field(default_factory=time.time)


class HealthSignalBus:
    """Pub/sub bus with a bounded recent-signal buffer (HealthSignalBus.scala:162-371)."""

    def __init__(self, buffer_size: int = 25) -> None:
        self._recent: CircularBuffer[HealthSignal] = CircularBuffer(buffer_size)
        self._subscribers: List[Callable[[HealthSignal], None]] = []
        # lifetime emit counts by severity level — the ring buffer forgets,
        # the scrape surface (metrics/exposition.health_collector) must not
        self.signal_counts: Dict[str, int] = {}

    def emit(self, name: str, level: str = "warning", source: str = "",
             metadata: Optional[dict] = None) -> HealthSignal:
        signal = HealthSignal(name=name, level=level, source=source,
                              metadata=metadata or {})
        self._recent.push(signal)
        self.signal_counts[level] = self.signal_counts.get(level, 0) + 1
        for fn in list(self._subscribers):
            try:
                fn(signal)
            except Exception:  # noqa: BLE001 — one subscriber must not break the bus
                logger.exception("health subscriber failed")
        return signal

    def signal_fn(self, source: str) -> Callable[[str, str], None]:
        """Adapter matching the components' ``on_signal(name, level)`` hook."""
        return lambda name, level: self.emit(name, level, source=source)

    def subscribe(self, fn: Callable[[HealthSignal], None]) -> None:
        self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[HealthSignal], None]) -> None:
        try:
            self._subscribers.remove(fn)
        except ValueError:
            pass

    def recent(self) -> List[HealthSignal]:
        return self._recent.to_list()


# -- windows + matchers -----------------------------------------------------------------


class SlidingSignalWindow:
    """Time window over signals, advancing on expiry or on buffer threshold
    (WindowSlider semantics: slide when the buffer exceeds ``advance_threshold``)."""

    def __init__(self, window_s: float, advance_threshold: int = 10) -> None:
        self.window_s = window_s
        self.advance_threshold = advance_threshold
        self._buffer: Deque[HealthSignal] = deque()

    def add(self, signal: HealthSignal) -> None:
        self._buffer.append(signal)
        self.advance(signal.timestamp)
        while len(self._buffer) > self.advance_threshold:
            self._buffer.popleft()

    def advance(self, now: Optional[float] = None) -> None:
        cutoff = (now if now is not None else time.time()) - self.window_s
        while self._buffer and self._buffer[0].timestamp < cutoff:
            self._buffer.popleft()

    def signals(self) -> List[HealthSignal]:
        return list(self._buffer)

    def __len__(self) -> int:
        return len(self._buffer)


class NameEqualsMatcher:
    """SignalNameEqualsMatcher: fire when one signal's name matches exactly."""

    def __init__(self, name: str) -> None:
        self.name = name

    def matches(self, signal: HealthSignal, window: SlidingSignalWindow) -> bool:
        return signal.name == self.name


class RegexMatcher:
    """SignalNamePatternMatcher: fire when the signal name matches a regex."""

    def __init__(self, pattern: str | Pattern[str]) -> None:
        self.pattern = re.compile(pattern)

    def matches(self, signal: HealthSignal, window: SlidingSignalWindow) -> bool:
        return self.pattern.search(signal.name) is not None

    def __repr__(self) -> str:  # pragma: no cover
        return f"RegexMatcher({self.pattern.pattern!r})"


class RepeatingSignalMatcher:
    """RepeatingSignalMatcher: fire when a signal repeats >= ``times`` within the
    window (the sliding-window stream's raison d'être)."""

    def __init__(self, times: int, inner: NameEqualsMatcher | RegexMatcher) -> None:
        self.times = times
        self.inner = inner

    def matches(self, signal: HealthSignal, window: SlidingSignalWindow) -> bool:
        if not self.inner.matches(signal, window):
            return False
        hits = sum(1 for s in window.signals() if self.inner.matches(s, window))
        return hits >= self.times


# -- supervisor -------------------------------------------------------------------------


@dataclass
class _Registration:
    """One supervised component (HealthRegistration analog)."""

    name: str
    component: Controllable
    restart_matchers: Sequence[object]
    shutdown_matchers: Sequence[object] = ()
    window: SlidingSignalWindow = field(default_factory=lambda: SlidingSignalWindow(10.0))
    restarts: int = 0


class HealthSupervisor:
    """Pattern → restart/shutdown supervision over the signal bus
    (HealthSupervisorActor.scala:63-111)."""

    def __init__(self, bus: HealthSignalBus, config: Config | None = None) -> None:
        self.bus = bus
        cfg = config or default_config()
        self.max_restarts = cfg.get_int("surge.health.supervisor-restart-max", 3)
        self._window_s = cfg.get_seconds("surge.health.window-frequency-ms", 10_000)
        self._threshold = cfg.get_int("surge.health.window-buffer-size", 10)
        self._registrations: Dict[str, _Registration] = {}
        self._started = False
        # restart/shutdown dispatches in flight: retained so a failing
        # action surfaces its exception instead of dying silently with a
        # GC'd task (the supervisor IS the last line of defense)
        self._actions: set = set()

    def start(self) -> None:
        if not self._started:
            self.bus.subscribe(self._on_signal)
            self._started = True

    def stop(self) -> None:
        if self._started:
            self.bus.unsubscribe(self._on_signal)
            self._started = False

    def register(self, name: str, component: Controllable,
                 restart_patterns: Sequence[object],
                 shutdown_patterns: Sequence[object] = ()) -> None:
        """registerSupervisedComponent: the component's Controllable is driven when a
        pattern matches (restartSignalPatterns, AggregateStateStoreKafkaStreams:74-76)."""
        self._registrations[name] = _Registration(
            name=name, component=component, restart_matchers=list(restart_patterns),
            shutdown_matchers=list(shutdown_patterns),
            window=SlidingSignalWindow(self._window_s, self._threshold))

    def registered(self) -> List[str]:
        return sorted(self._registrations)

    def restart_counts(self) -> Dict[str, int]:
        """Restarts driven per registered component (scrape-surface view of
        each registration's budget consumption)."""
        return {name: reg.restarts
                for name, reg in self._registrations.items()}

    async def restart_component(self, name: str) -> None:
        """Operator-initiated restart of a registered component (the JMX MBean
        restart op): same budget/signal path a matched pattern takes.
        Raises KeyError for unknown names."""
        reg = self._registrations[name]
        await self._restart(reg, HealthSignal(name="admin.restart-requested",
                                              level="trace", source=name))

    def _on_signal(self, signal: HealthSignal) -> None:
        for reg in self._registrations.values():
            reg.window.add(signal)
            if any(m.matches(signal, reg.window) for m in reg.shutdown_matchers):
                spawn_reaped(self._actions, self._shutdown(reg, signal),
                             f"supervisor shutdown of {reg.name}")
            elif any(m.matches(signal, reg.window) for m in reg.restart_matchers):
                spawn_reaped(self._actions, self._restart(reg, signal),
                             f"supervisor restart of {reg.name}")

    async def _restart(self, reg: _Registration, signal: HealthSignal) -> None:
        if reg.restarts >= self.max_restarts:
            logger.error("supervisor: %s exceeded restart budget; shutting down", reg.name)
            await self._shutdown(reg, signal)
            return
        reg.restarts += 1
        try:
            await reg.component.restart()
            self.bus.emit("health.component-restarted", "trace", source=reg.name,
                          metadata={"trigger": signal.name, "restarts": reg.restarts})
        except Exception:  # noqa: BLE001
            logger.exception("supervisor: restart of %s failed", reg.name)
            self.bus.emit("health.component-restart-failed", "error", source=reg.name)

    async def _shutdown(self, reg: _Registration, signal: HealthSignal) -> None:
        try:
            await reg.component.shutdown()
            self.bus.emit("health.component-shutdown", "trace", source=reg.name,
                          metadata={"trigger": signal.name})
        except Exception:  # noqa: BLE001
            logger.exception("supervisor: shutdown of %s failed", reg.name)


@dataclass
class HealthCheck:
    """Nested component health (surge.health.SurgeHealthCheck ask-chain analog)."""

    name: str
    status: str  # "up" | "down" | "degraded"
    components: List["HealthCheck"] = field(default_factory=list)

    def is_healthy(self) -> bool:
        return self.status == "up" and all(c.is_healthy() for c in self.components)
