"""Event-loop starvation prober.

The asyncio re-derivation of the reference's thread-starvation detector
(``ExecutionContextProber`` — internal/utils/ExecutionContextProber.scala:17-172,
config ``surge.execution-context-prober.*`` in common reference.conf:291-302): the
reference schedules no-op probes on a target ExecutionContext and warns when they
don't run within a timeout. Here the hazard is blocking the single event loop (long
synchronous serialization, accidental sync IO, an unyielding fold), so the probe is a
timestamped ``sleep(interval)`` whose *lateness* measures how long the loop was
unavailable; sustained lateness past the threshold emits a health signal and a log
warning with the same "possible starvation" message intent.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional

from surge_tpu.common import logger
from surge_tpu.config import Config, default_config


class EventLoopProber:
    """Measures event-loop responsiveness; signals on sustained starvation."""

    def __init__(self, config: Config | None = None,
                 on_signal: Optional[Callable[[str, str], None]] = None) -> None:
        cfg = config or default_config()
        self.interval_s = cfg.get_seconds("surge.event-loop-prober.interval-ms", 1000)
        self.threshold_s = cfg.get_seconds("surge.event-loop-prober.threshold-ms", 200)
        # consecutive late probes before signalling (the reference probes in rounds
        # of numProbes before deciding)
        self.late_probes = cfg.get_int("surge.event-loop-prober.late-probes", 3)
        self._on_signal = on_signal or (lambda name, level: None)
        self._task: Optional[asyncio.Task] = None
        self._late_streak = 0
        self.max_delay_s = 0.0
        self.last_delay_s = 0.0
        self.starvation_events = 0

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())
            self._task.set_name("surge-event-loop-prober")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            t0 = time.perf_counter()
            await asyncio.sleep(self.interval_s)
            delay = (time.perf_counter() - t0) - self.interval_s
            self.last_delay_s = delay
            self.max_delay_s = max(self.max_delay_s, delay)
            if delay > self.threshold_s:
                self._late_streak += 1
                if self._late_streak >= self.late_probes:
                    self.starvation_events += 1
                    self._late_streak = 0
                    logger.warning(
                        "possible event-loop starvation: probe %.0fms late "
                        "(threshold %.0fms) %d times in a row",
                        delay * 1e3, self.threshold_s * 1e3, self.late_probes)
                    self._on_signal("event-loop.starvation", "warning")
            else:
                self._late_streak = 0
