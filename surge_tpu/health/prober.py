"""Event-loop starvation prober.

The asyncio re-derivation of the reference's thread-starvation detector
(``ExecutionContextProber`` — internal/utils/ExecutionContextProber.scala:17-172,
config ``surge.execution-context-prober.*`` in common reference.conf:291-302): the
reference schedules no-op probes on a target ExecutionContext and warns when they
don't run within a timeout. Here the hazard is blocking the single event loop (long
synchronous serialization, accidental sync IO, an unyielding fold), so the probe is a
timestamped ``sleep(interval)`` whose *lateness* measures how long the loop was
unavailable; sustained lateness past the threshold emits a health signal and a log
warning with the same "possible starvation" message intent.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Callable, Optional

from surge_tpu.common import logger
from surge_tpu.config import Config, default_config


class BrokerLivenessProber:
    """Thread-based peer-liveness prober for the (synchronous) log broker:
    pings a target on an interval and declares it DEAD after a streak of
    consecutive failures — the failure-detector half of automatic leader
    failover (``surge.log.failover.*``). A follower runs one against its
    leader; ``on_dead`` fires exactly once (self-promotion), after which the
    prober retires itself.

    Deliberately conservative: one slow probe never kills a leader — only an
    unbroken failure streak does — and the declare threshold × interval is
    the unavailability floor an operator tunes against split-brain risk
    (docs/operations.md failover runbook)."""

    def __init__(self, target: str, ping: Callable[[], None],
                 config: Config | None = None,
                 on_dead: Optional[Callable[[], None]] = None,
                 on_signal: Optional[Callable[[str, str], None]] = None,
                 flight=None) -> None:
        cfg = config or default_config()
        self.target = target
        #: optional FlightRecorder: the promotion DECISION (leader declared
        #: dead) is the failover timeline's opening event — it must be
        #: reconstructable even though no RPC ever carries it
        self.flight = flight
        self.interval_s = cfg.get_seconds(
            "surge.log.failover.probe-interval-ms", 1_000)
        self.failures_needed = max(1, cfg.get_int(
            "surge.log.failover.probe-failures", 3))
        self._ping = ping
        self._on_dead = on_dead or (lambda: None)
        self._on_signal = on_signal or (lambda name, level: None)
        #: bootstrap grace: a peer NEVER seen alive is probably still booting
        #: (follower started first) — promoting over it would split the brain
        #: the moment it arrives, so the declare threshold is multiplied
        #: until the first successful probe. Bounded, not infinite: a leader
        #: that truly never comes up must still fail over eventually.
        self.bootstrap_factor = max(1, cfg.get_int(
            "surge.log.failover.bootstrap-grace-factor", 10))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.failure_streak = 0
        self.declared_dead = False
        self.probes = 0
        self.ever_alive = False
        #: re-arms after a retired declaration (lost campaigns, stand-downs):
        #: a broker that loses N consecutive elections must STILL detect the
        #: next real leader death — this counts the proof
        self.rearms = 0

    def start(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name=f"surge-broker-prober-{self.target}",
                daemon=True)
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(self.interval_s + 2.0)
        self._thread = None

    def reset(self) -> None:
        """Re-arm after a retired declaration (quorum candidacy lost its vote
        round: the leader may yet return, or the true new leader's stream
        will repoint us) — clears the dead verdict and restarts probing.
        Callable from the prober's own on_dead callback: the current run is
        RETIRING (it returns right after on_dead), so start() must spawn a
        fresh thread instead of seeing the still-alive current one and
        doing nothing. Callable from ANY other thread too: a retiring run
        that has not unwound yet is waited out briefly, so the re-arm can
        never be swallowed by start() observing a corpse as alive (the
        repeated-election case — stand down, re-arm, stand down, re-arm —
        must stay armed however many campaigns are lost)."""
        self.declared_dead = False
        self.failure_streak = 0
        self.rearms += 1
        thread = self._thread
        if thread is threading.current_thread():
            self._thread = None
        elif thread is not None and thread.is_alive():
            self._stop.set()
            thread.join(self.interval_s + 2.0)
            self._stop.clear()
            self._thread = None
        self.start()

    def retarget(self, target: str) -> None:
        """Point the prober at a NEW leader (cluster repoint after another
        broker won promotion): fresh streak, bootstrap grace re-applies until
        the new leader is seen alive once."""
        self.stop()
        self.target = target
        self.failure_streak = 0
        self.declared_dead = False
        self.ever_alive = False
        self._stop.clear()
        self.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.probes += 1
            try:
                self._ping()
                self.failure_streak = 0
                self.ever_alive = True
            except Exception as exc:  # noqa: BLE001 — the failure being counted
                needed = self.failures_needed * (
                    1 if self.ever_alive else self.bootstrap_factor)
                self.failure_streak += 1
                logger.warning("broker %s probe failed (%d/%d): %r",
                               self.target, self.failure_streak,
                               needed, exc)
                self._on_signal("broker.probe-failed", "warning")
                if self.failure_streak >= needed:
                    self.declared_dead = True
                    logger.error("broker %s declared DEAD after %d "
                                 "consecutive probe failures", self.target,
                                 self.failure_streak)
                    self._on_signal("broker.dead", "error")
                    if self.flight is not None:
                        self.flight.record("role.promote-decision",
                                           dead_leader=self.target,
                                           failure_streak=self.failure_streak,
                                           probes=self.probes)
                    try:
                        self._on_dead()
                    except Exception:  # noqa: BLE001
                        logger.exception("on_dead callback failed")
                    return  # one-shot: the promotion owns what happens next


class EventLoopProber:
    """Measures event-loop responsiveness; signals on sustained starvation."""

    def __init__(self, config: Config | None = None,
                 on_signal: Optional[Callable[[str, str], None]] = None) -> None:
        cfg = config or default_config()
        self.interval_s = cfg.get_seconds("surge.event-loop-prober.interval-ms", 1000)
        self.threshold_s = cfg.get_seconds("surge.event-loop-prober.threshold-ms", 200)
        # consecutive late probes before signalling (the reference probes in rounds
        # of numProbes before deciding)
        self.late_probes = cfg.get_int("surge.event-loop-prober.late-probes", 3)
        self._on_signal = on_signal or (lambda name, level: None)
        self._task: Optional[asyncio.Task] = None
        self._late_streak = 0
        self.max_delay_s = 0.0
        self.last_delay_s = 0.0
        self.starvation_events = 0

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())
            self._task.set_name("surge-event-loop-prober")

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            t0 = time.perf_counter()
            await asyncio.sleep(self.interval_s)
            delay = (time.perf_counter() - t0) - self.interval_s
            self.last_delay_s = delay
            self.max_delay_s = max(self.max_delay_s, delay)
            if delay > self.threshold_s:
                self._late_streak += 1
                if self._late_streak >= self.late_probes:
                    self.starvation_events += 1
                    self._late_streak = 0
                    logger.warning(
                        "possible event-loop starvation: probe %.0fms late "
                        "(threshold %.0fms) %d times in a row",
                        delay * 1e3, self.threshold_s * 1e3, self.late_probes)
                    self._on_signal("event-loop.starvation", "warning")
            else:
                self._late_streak = 0
