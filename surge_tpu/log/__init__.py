"""Log transport layer — the durability/replication substrate seam.

Equivalent of the reference's Kafka client layer (modules/common/src/main/scala/surge/
kafka/KafkaProducer.scala:18-265, KafkaConsumer.scala:17-132, KafkaAdminClient.scala) and
the broker semantics the engine relies on: transactional atomic multi-topic appends,
producer-epoch zombie fencing, read_committed isolation, compacted state topics, and
consumer-lag queries. Every engine test in the reference runs against this seam
(SURVEY.md §4); :class:`InMemoryLog` is the EmbeddedKafka analog and the default
transport for single-process engines.
"""

from surge_tpu.log.transport import (
    LogRecord,
    LogTransport,
    ProducerFencedError,
    TopicSpec,
    TransactionalProducer,
    TransactionStateError,
)
from surge_tpu.log.memory import InMemoryLog
from surge_tpu.log.file import FileLog
from surge_tpu.log.compactor import CompactionStats, LogCompactor


def __getattr__(name):
    # grpc-backed broker pieces load lazily so `import surge_tpu` does not make
    # grpc a hard dependency of replay-only / FileLog-only consumers
    if name == "GrpcLogTransport":
        from surge_tpu.log.client import GrpcLogTransport
        return GrpcLogTransport
    if name == "LogServer":
        from surge_tpu.log.server import LogServer
        return LogServer
    raise AttributeError(name)

__all__ = [
    "CompactionStats",
    "FileLog",
    "LogCompactor",
    "GrpcLogTransport",
    "LogServer",
    "InMemoryLog",
    "LogRecord",
    "LogTransport",
    "ProducerFencedError",
    "TopicSpec",
    "TransactionalProducer",
    "TransactionStateError",
]
