"""GrpcLogTransport — the LogTransport protocol over a remote LogServer.

The KafkaProducer/KafkaConsumer-wrapper role (KafkaProducer.scala:18-265,
KafkaConsumer.scala:17-132): thin, promise-free blocking calls against a remote
broker, with transactions buffered locally and shipped atomically at commit, and
fencing surfaced as :class:`ProducerFencedError`. Calls use a synchronous gRPC
channel — they block the calling thread for one loopback/network round trip, which
is the same envelope the reference's producer calls have against a broker.

``wait_for_append`` long-polls the server from an executor thread so the event loop
stays free (the dedicated poll-thread pattern of KafkaConsumerTrait).
"""

from __future__ import annotations

import asyncio
import threading
import time
from concurrent.futures import Future as _ConcurrentFuture
from random import Random
from typing import Dict, List, Mapping, Optional, Sequence

import grpc

from surge_tpu.log import log_service_pb2 as pb
from surge_tpu.log import native_gate
from surge_tpu.log.common import lazy_read_reply, lazy_txn_reply
from surge_tpu.log.server import METHODS, SERVICE, msg_to_record, record_to_msg
from surge_tpu.log.transport import (
    LogRecord,
    NotLeaderError,
    ProducerFencedError,
    TopicSpec,
    TransactionStateError,
)

#: native reply-leg deserializers (log/common.py): one C++ index call per
#: reply + lazy decode-on-access views instead of a protobuf parse + one
#: frozen LogRecord per record. Registered only while the native read
#: decode is enabled; anything else keeps the protobuf classes.
_LAZY_DESERIALIZERS = {"Read": lazy_read_reply, "Transact": lazy_txn_reply}


def _reply_records(reply) -> List[LogRecord]:
    """The reply's committed records: lazy views pass through (list), a
    protobuf reply converts per message (the pre-view path)."""
    recs = reply.records
    if isinstance(recs, list):
        return recs
    return [msg_to_record(m) for m in recs]


def _raise_for(reply: pb.TxnReply) -> None:
    if reply.ok:
        return
    if reply.error_kind == "fenced":
        raise ProducerFencedError(reply.error)
    if reply.error_kind == "state":
        raise TransactionStateError(reply.error)
    raise RuntimeError(f"log server error: {reply.error}")


class PipelinedCommit:
    """One in-flight pipelined transaction: the txn_seq assigned at dispatch,
    the records it carries, and the concurrent future its Transact resolves.
    ``retry()``-by-the-publisher resends the SAME seq + records verbatim so a
    commit whose reply was lost is answered from the broker's dedup cache
    instead of being appended twice."""

    __slots__ = ("seq", "records", "future", "producer")

    def __init__(self, seq: int, records: List[LogRecord],
                 producer: "GrpcTxnProducer") -> None:
        self.seq = seq
        self.records = records
        self.producer = producer
        self.future: "_ConcurrentFuture" = _ConcurrentFuture()


class GrpcTxnProducer:
    """Client half of a server-side transactional producer (one token).

    Commits are idempotent over the wire: every commit/send_immediate carries a
    per-token sequence number, and a lost reply is retried with the SAME number —
    the server answers a replayed sequence from its cached reply instead of
    appending the transaction twice (the Kafka idempotent-producer role,
    KafkaProducerActorImpl.scala:161-165 `enable.idempotence`).

    ``commit_pipelined`` is the bounded-window variant (the
    max.in.flight.requests.per.connection role): the seq is assigned at
    dispatch and the Transact ships from the transport's pipeline pool
    WITHOUT waiting for earlier replies — the broker's per-producer in-order
    apply gate sequences them, and its dedup window (not just the last seq)
    answers replays anywhere in the window. The caller bounds how many
    dispatches it keeps un-awaited (``surge.producer.max-in-flight``).
    """

    def __init__(self, transport: "GrpcLogTransport", token: int,
                 generation: int = 0, next_seq: int = 1) -> None:
        self._transport = transport
        self._token = token
        self._generation = generation  # transport generation at open time
        self._buffer: Optional[List[LogRecord]] = None
        self._fenced = False
        self._next_seq = next_seq

    @property
    def fenced(self) -> bool:
        """Whether this producer has observed itself fenced.

        Lazy, unlike InMemoryTxnProducer: it flips only after an operation
        fails with ``error_kind="fenced"`` — a proactive poll can read a stale
        False until the next wire operation. The publisher FSM only consults it
        after a failed publish, where the two contracts agree; callers needing
        a fresh answer should attempt an operation rather than poll this.
        """
        return self._fenced

    @property
    def in_transaction(self) -> bool:
        return self._buffer is not None

    def begin(self) -> None:
        if self._buffer is not None:
            raise TransactionStateError("transaction already open")
        self._buffer = []

    def send(self, record: LogRecord) -> None:
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        self._buffer.append(record)

    def commit(self) -> Sequence[LogRecord]:
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        records, self._buffer = self._buffer, None
        try:
            reply = self._transport._transact(self._token, "commit", records,
                                              seq=self._next_seq,
                                              generation=self._generation)
        except ProducerFencedError:
            self._fenced = True
            raise
        self._check_fence(reply)
        _raise_for(reply)
        self._next_seq += 1
        return _reply_records(reply)

    def commit_unsequenced(self) -> Sequence[LogRecord]:
        """Commit WITHOUT an idempotency seq (txn_seq=0): for epoch markers
        like the publisher's init flush record, whose duplicates are harmless
        and which must not consume the broker's one-shot reopen-absorption
        window (a landed-but-unacked data batch needs it after a restart)."""
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        records, self._buffer = self._buffer, None
        try:
            reply = self._transport._transact(self._token, "commit", records,
                                              seq=0,
                                              generation=self._generation)
        except ProducerFencedError:
            self._fenced = True
            raise
        self._check_fence(reply)
        _raise_for(reply)
        return _reply_records(reply)

    def replay_commit(self, records: Sequence[LogRecord],
                      seq: Optional[int] = None) -> Sequence[LogRecord]:
        """Re-ship an ALREADY-ACKED commit with its original seq — the
        consistency auditor's dedup probe. A healthy broker answers from its
        dedup window (cached reply: same offsets as the original ack); a
        broker that appends again has a dedup-window hole. ``seq`` defaults
        to the last acked sequence (``_next_seq - 1``) and the counter does
        NOT advance — this is a replay, not a new commit."""
        if seq is None:
            seq = self._next_seq - 1
        if seq < 1:
            raise TransactionStateError("no acked commit to replay")
        try:
            reply = self._transport._transact(self._token, "commit",
                                              list(records), seq=seq,
                                              generation=self._generation)
        except ProducerFencedError:
            self._fenced = True
            raise
        self._check_fence(reply)
        _raise_for(reply)
        return _reply_records(reply)

    def commit_pipelined(self) -> PipelinedCommit:
        """Dispatch the buffered transaction without awaiting the reply."""
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        records, self._buffer = self._buffer, None
        seq = self._next_seq
        self._next_seq += 1
        handle = PipelinedCommit(seq, list(records), self)
        self._transport._submit_transact(self, handle)
        return handle

    def retry_pipelined(self, handle: PipelinedCommit) -> PipelinedCommit:
        """Resend a failed pipelined commit VERBATIM (same seq, same records)."""
        if not handle.future.done():
            raise TransactionStateError("pipelined commit still in flight")
        handle.future = _ConcurrentFuture()
        self._transport._submit_transact(self, handle)
        return handle

    def abort(self) -> None:
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        self._buffer = None  # records never left this process

    def send_immediate(self, record: LogRecord) -> LogRecord:
        try:
            reply = self._transport._transact(self._token, "send_immediate",
                                              [record], seq=self._next_seq,
                                              generation=self._generation)
        except ProducerFencedError:
            self._fenced = True
            raise
        self._check_fence(reply)
        _raise_for(reply)
        self._next_seq += 1
        return _reply_records(reply)[0]

    def _check_fence(self, reply: pb.TxnReply) -> None:
        if not reply.ok and reply.error_kind == "fenced":
            self._fenced = True


class GrpcLogTransport:
    """:class:`surge_tpu.log.transport.LogTransport` against a remote LogServer.

    ``target`` may name SEVERAL brokers (comma-separated, or a list): the first
    is preferred, the rest are failover order (a leader + its ship-on-commit
    followers, the acks=all role of the reference's replicated Kafka cluster).
    When the current broker becomes unreachable the transport rolls to the next
    one; producers opened against the dead broker observe a **generation bump**
    and surface :class:`ProducerFencedError`, which drives the publisher's
    existing fenced → re-initialize ladder — it re-opens on the new broker and,
    thanks to replicated txn-dedup state, resumes its idempotency numbering
    without duplicating an acked-but-reply-lost commit."""

    #: reads/end_offset are blocking RPCs here — callers sharing an event
    #: loop (the resident plane's freshness checks) must ride the executor
    is_remote = True

    def __init__(self, target, config=None,
                 auto_create_partitions: int = 1, tracer=None,
                 metrics=None) -> None:
        self.tracer = tracer  # client-side broker-call spans (None = zero cost)
        self.metrics = metrics  # EngineMetrics quiver: failover counters (optional)
        #: jitter source for failover/redirect backoff: simultaneous clients
        #: re-probing a promoting broker must not arrive in lockstep
        self._rng = Random()
        if isinstance(target, str):
            self.targets = [t.strip() for t in target.split(",") if t.strip()]
        else:
            self.targets = list(target)
        if not self.targets:
            raise ValueError("need at least one broker target")
        self.target = self.targets[0]  # current
        #: endpoints LEARNED from NOT_LEADER hints (vs the configured
        #: failover order): a learned hint is advisory and expires — on the
        #: next redirect, or on a connect failure — so a moved-back
        #: partition never ping-pongs through a dead ex-leader
        self._learned: set = set()
        self._config = config
        from surge_tpu.config import default_config as _dc

        # a commit may legitimately block for the server's replication-ack wait;
        # the client deadline must sit ABOVE it or slow-but-alive brokers would
        # be misread as dead
        self._transact_timeout = max(
            10.0, 2.0 * (config or _dc()).get_seconds(
                "surge.log.replication-ack-timeout-ms", 5_000))
        self._calls: Dict[str, object] = {}
        self._channel = None
        self.generation = 0
        self._auto_create_partitions = auto_create_partitions
        self._topics: Dict[str, TopicSpec] = {}  # local spec cache
        self._lock = threading.Lock()
        # pipelined Transact dispatch pool (sync stubs block a thread per
        # in-flight call): sized for several lanes' windows; lazy so
        # non-pipelining users never pay the threads
        self._pipeline_pool = None
        self._connect(0)

    def _connect(self, index: int) -> None:
        from surge_tpu.remote.security import secure_sync_channel

        if self._channel is not None:
            try:
                self._channel.close()
            except Exception:  # noqa: BLE001
                pass
        self.target = self.targets[index % len(self.targets)]
        self._channel = secure_sync_channel(self.target, self._config)
        # an explicit test/bench pin (set_decode_enabled) wins; otherwise
        # THIS transport's config decides — the operator kill-switch on an
        # explicitly-configured client must reach its reply decode, not
        # just the ambient default (the same per-instance-config contract
        # FileLog's reads honor)
        pin = native_gate.decode_pinned()
        if pin is not None:
            lazy_ok = pin
        elif self._config is not None:
            lazy_ok = native_gate.enabled(self._config)
        else:
            lazy_ok = native_gate.decode_enabled()
        for name, (req_cls, reply_cls) in METHODS.items():
            deserializer = reply_cls.FromString
            if lazy_ok:
                deserializer = _LAZY_DESERIALIZERS.get(name, deserializer)
            self._calls[name] = self._channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=deserializer)

    def _failover(self, from_generation: int) -> None:
        t0 = time.perf_counter()
        with self._lock:
            if self.generation != from_generation:
                return  # another caller already rolled
            self.generation += 1
            failed = self.target
            index = self.targets.index(failed)
            if failed in self._learned and len(self.targets) > 1:
                # connect failure on a LEARNED endpoint: evict it from the
                # rotation entirely (ISSUE 13 satellite — configured targets
                # are the operator's failover order and stay; a stale hint
                # kept forever would have every later roll ping-pong
                # through the dead broker)
                self.targets.remove(failed)
                self._learned.discard(failed)
                self._connect(index % len(self.targets))
            else:
                self._connect(index + 1)
        if self.metrics is not None:
            self.metrics.failover_rolls.record()
            self.metrics.failover_redirect_timer.record_ms(
                (time.perf_counter() - t0) * 1000.0)

    def _redirect(self, from_generation: int, target: str) -> bool:
        """Follow a NOT_LEADER redirect: reconnect to the hinted broker
        (learning it if absent from the endpoint list) and bump the
        generation so producers opened against the old broker re-open. A
        hint pointing at the broker we are already on is a follower whose
        leader has not promoted yet — not followable; the caller backs off
        (jittered) and retries instead."""
        if not target:
            return False
        t0 = time.perf_counter()
        with self._lock:
            if self.generation != from_generation:
                return True  # another caller already moved
            if target == self.target:
                return False
            # a fresh hint INVALIDATES earlier learned ones (ISSUE 13
            # satellite): after handoffs A→B→A the stale B endpoint must
            # leave the rotation — the endpoint being redirected AWAY from
            # included — or the next failover cycles through a broker that
            # may be dead by then
            stale = [t for t in self._learned if t != target]
            for t in stale:
                self.targets.remove(t)
                self._learned.discard(t)
            if target not in self.targets:
                self.targets.append(target)
                self._learned.add(target)
            self.generation += 1
            self._connect(self.targets.index(target))
        if self.metrics is not None:
            self.metrics.failover_redirects.record()
            self.metrics.failover_redirect_timer.record_ms(
                (time.perf_counter() - t0) * 1000.0)
        return True

    def _jittered(self, backoff: float) -> float:
        """Randomized sleep in [backoff/2, backoff): retry storms against a
        broker mid-promotion decorrelate instead of arriving in waves."""
        return backoff * (0.5 + 0.5 * self._rng.random())

    def _backoff_sleep(self, backoff: float) -> None:
        """Jittered retry sleep, recorded into the client failover backoff
        histogram (with the active span's trace id as the bucket exemplar
        when the registry captures them — the patience a command actually
        paid riding out a failover is visible AND traceable)."""
        delay = self._jittered(backoff)
        time.sleep(delay)
        if self.metrics is not None:
            self.metrics.failover_backoff_timer.record_ms(delay * 1000.0)

    def _span_and_metadata(self, name: str, **attrs):
        """(span, gRPC metadata) for one broker call — the traceparent crosses
        to the LogServer as call metadata so the broker's span chains under the
        client's. WaitForAppend is excluded: a tailing indexer's long-poll
        ticks would drown every other span."""
        if self.tracer is None or name == "WaitForAppend":
            return None, None
        from surge_tpu.tracing import active_span, inject_context

        # parent on the caller's active span (the publisher's flush span —
        # copied into the pipeline pool's threads at dispatch): the broker
        # call's span, and every failover-histogram exemplar recorded under
        # it, carries the ORIGINATING command's trace id
        span = self.tracer.start_span(f"log.{name}", parent=active_span())
        span.set_attribute("broker", self.target)
        for k, v in attrs.items():
            span.set_attribute(k, v)
        return span, tuple(inject_context(span.context).items())

    def _invoke(self, name: str, request, timeout: float = 10.0):
        """Call with broker failover: UNAVAILABLE rolls to the next target and
        retries, up to one full cycle through the broker list. DEADLINE retries
        in place — a slow-but-alive broker must NOT be treated as dead (writing
        to a follower while its leader still serves would fork the logs)."""
        span, metadata = self._span_and_metadata(name)
        if span is None:
            return self._invoke_attempts(name, request, timeout, metadata, span)
        with span:  # records exceptions + finishes
            return self._invoke_attempts(name, request, timeout, metadata, span)

    def _invoke_attempts(self, name: str, request, timeout: float,
                         metadata, span):
        last = None
        for attempt in range(max(len(self.targets), 1) + 1):
            gen = self.generation
            try:
                return self._calls[name](request, timeout=timeout,
                                         metadata=metadata)
            except grpc.RpcError as exc:
                code = exc.code() if hasattr(exc, "code") else None
                # CANCELLED happens when another thread's failover closed the
                # shared channel mid-call: retry on the fresh stubs
                if code not in (grpc.StatusCode.UNAVAILABLE,
                                grpc.StatusCode.DEADLINE_EXCEEDED,
                                grpc.StatusCode.CANCELLED):
                    raise
                last = exc
                if span is not None:
                    span.add_event("retry", {"attempt": attempt,
                                             "code": str(code)})
                if attempt >= max(len(self.targets), 1):
                    break
                if (code == grpc.StatusCode.UNAVAILABLE
                        and len(self.targets) > 1):
                    self._failover(gen)
                self._backoff_sleep(0.1)
        raise last

    # -- topics ---------------------------------------------------------------------------

    def create_topic(self, spec: TopicSpec) -> None:
        self._invoke("CreateTopic", pb.CreateTopicRequest(spec=pb.TopicSpecMsg(
            name=spec.name, partitions=spec.partitions, compacted=spec.compacted)))
        with self._lock:
            self._topics[spec.name] = spec

    def topic(self, name: str) -> TopicSpec:
        with self._lock:
            hit = self._topics.get(name)
        if hit is not None:
            return hit
        reply = self._invoke("GetTopic", pb.TopicRequest(name=name))
        if not reply.found:
            # parity with InMemoryLog: unknown topics auto-create
            spec = TopicSpec(name, self._auto_create_partitions)
            self.create_topic(spec)
            return spec
        spec = TopicSpec(reply.spec.name, reply.spec.partitions, reply.spec.compacted)
        with self._lock:
            self._topics[name] = spec
        return spec

    def num_partitions(self, name: str) -> int:
        return self.topic(name).partitions

    # -- producers ------------------------------------------------------------------------

    def transactional_producer(self, transactional_id: str) -> GrpcTxnProducer:
        """Open a producer ON THE LEADER: a follower answers a NOT_LEADER
        redirect, which is followed (hint) or retried with jittered backoff
        (mid-promotion: the follower IS the next leader, it just has not
        promoted yet) — the publisher's re-init ladder sits above this, so
        bounded patience here beats failing fast."""
        backoff = 0.1
        last_error = ""
        for attempt in range(8):
            gen = self.generation
            reply = self._invoke("OpenProducer",
                                 pb.OpenProducerRequest(
                                     transactional_id=transactional_id))
            if not reply.error_kind:
                return GrpcTxnProducer(self, reply.producer_token,
                                       generation=self.generation,
                                       next_seq=reply.last_txn_seq + 1)
            last_error = reply.error
            if reply.error_kind != "not_leader":
                raise TransactionStateError(reply.error)
            if not self._redirect(gen, reply.leader_hint):
                self._backoff_sleep(backoff)
                backoff = min(backoff * 2, 1.0)
        raise NotLeaderError(
            f"no leader found for producer open after redirects: {last_error}",
            leader_hint="")

    def _submit_transact(self, producer: GrpcTxnProducer,
                         handle: PipelinedCommit) -> None:
        """Ship one pipelined commit from the pipeline pool; the handle's
        future resolves with the committed records (offsets assigned) or the
        same exceptions the synchronous ``commit()`` raises."""
        if self._pipeline_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            with self._lock:
                if self._pipeline_pool is None:
                    self._pipeline_pool = ThreadPoolExecutor(
                        max_workers=16, thread_name_prefix="surge-txn-pipe")
        # carry the caller's contextvars (the active span above all) into
        # the pool thread: a retry/backoff recorded there captures the
        # dispatching command's trace id as its histogram exemplar instead
        # of reading an empty context
        import contextvars

        ctx = contextvars.copy_context()
        self._pipeline_pool.submit(ctx.run, self._pipelined_call, producer,
                                   handle)

    def _pipelined_call(self, producer: GrpcTxnProducer,
                        handle: PipelinedCommit) -> None:
        try:
            reply = self._transact(producer._token, "commit", handle.records,
                                   seq=handle.seq,
                                   generation=producer._generation)
            producer._check_fence(reply)
            _raise_for(reply)
            handle.future.set_result(_reply_records(reply))
        except ProducerFencedError as exc:
            producer._fenced = True
            handle.future.set_exception(exc)
        except BaseException as exc:  # noqa: BLE001 — surface to the awaiter
            handle.future.set_exception(exc)

    def _transact(self, token: int, op: str, records: Sequence[LogRecord],
                  seq: int = 0, attempts: int = 4,
                  generation: Optional[int] = None) -> pb.TxnReply:
        span, metadata = self._span_and_metadata(
            "Transact", op=op, txn_seq=seq, records=len(records))
        if span is None:
            return self._transact_attempts(token, op, records, seq, attempts,
                                           generation, metadata, span)
        with span:  # records exceptions + finishes
            return self._transact_attempts(token, op, records, seq, attempts,
                                           generation, metadata, span)

    def _transact_attempts(self, token: int, op: str,
                           records: Sequence[LogRecord], seq: int,
                           attempts: int, generation: Optional[int],
                           metadata, span) -> pb.TxnReply:
        request = pb.TxnRequest(
            producer_token=token, op=op, txn_seq=seq,
            records=[record_to_msg(r) for r in records])
        backoff = 0.05
        for attempt in range(attempts):
            if generation is not None and generation != self.generation:
                # the transport failed over to another broker since this
                # producer was opened: its token is meaningless there. Surface
                # as fencing — the publisher's fenced → re-initialize ladder
                # re-opens on the new broker and (replicated dedup) resumes its
                # idempotency numbering.
                raise ProducerFencedError(
                    "broker failover: producer must re-open")
            try:
                reply = self._calls["Transact"](request,
                                                timeout=self._transact_timeout,
                                                metadata=metadata)
            except grpc.RpcError as exc:
                # Reply loss / transient broker trouble: retry the SAME txn_seq
                # so a commit the server did apply is answered from its dedup
                # cache, not appended again. DEADLINE and CANCELLED (another
                # thread's failover closed the channel) retry in place; only
                # UNAVAILABLE can mean broker death. Anything non-transient (or
                # seq-less ops, which we cannot safely replay) propagates.
                code = exc.code() if hasattr(exc, "code") else None
                transient = code in (grpc.StatusCode.UNAVAILABLE,
                                     grpc.StatusCode.DEADLINE_EXCEEDED,
                                     grpc.StatusCode.CANCELLED)
                if not seq or not transient or attempt == attempts - 1:
                    if (code == grpc.StatusCode.UNAVAILABLE
                            and len(self.targets) > 1
                            and generation is not None):
                        # current broker is gone: roll the transport so the
                        # NEXT open lands on a live one, then report fenced
                        self._failover(generation)
                        raise ProducerFencedError(
                            f"broker failover after {exc.code()}")
                    raise
                if span is not None:
                    span.add_event("retry", {"attempt": attempt,
                                             "code": str(code)})
                self._backoff_sleep(backoff)
                backoff = min(backoff * 2, 0.4)
                continue
            if not reply.ok and reply.error_kind == "not_leader":
                # the broker we were writing to is (now) a follower: follow
                # its redirect (or wait out a promotion with jittered
                # backoff), then surface as fencing — the publisher re-opens
                # on the leader and the replicated txn-dedup window keeps a
                # landed commit from doubling.
                if generation is not None:
                    self._redirect(generation, reply.leader_hint)
                    raise ProducerFencedError(
                        f"NOT_LEADER: {reply.error} "
                        f"(hint {reply.leader_hint or 'none'})")
                if attempt == attempts - 1:
                    raise NotLeaderError(reply.error, reply.leader_hint)
                self._backoff_sleep(backoff)
                backoff = min(backoff * 2, 0.4)
                continue
            if not reply.ok and reply.error_kind == "retriable" and seq:
                # replication timeout: the commit is applied on the broker but
                # not yet follower-acked. Retrying the SAME seq re-joins the
                # queued item server-side. If it never resolves, surface as
                # fencing — the reinit's OpenProducer numbers PAST the in-limbo
                # seq, so no different-payload reuse can occur.
                if attempt == attempts - 1:
                    raise ProducerFencedError(
                        f"replication unresolved: {reply.error}")
                self._backoff_sleep(backoff)
                backoff = min(backoff * 2, 0.4)
                continue
            return reply
        raise RuntimeError("unreachable")

    # -- reads ----------------------------------------------------------------------------

    def read(self, topic: str, partition: int, from_offset: int = 0,
             max_records: Optional[int] = None,
             isolation: str = "read_committed") -> Sequence[LogRecord]:
        del isolation  # the server's log already serves committed records only
        req = pb.ReadRequest(topic=topic, partition=partition,
                             from_offset=from_offset)
        if max_records is not None:
            req.has_max = True
            req.max_records = max_records
        reply = self._invoke("Read", req)
        return _reply_records(reply)

    def end_offset(self, topic: str, partition: int,
                   isolation: str = "read_committed") -> int:
        del isolation
        self.topic(topic)  # auto-create parity
        return self._invoke("EndOffset", pb.OffsetRequest(
            topic=topic, partition=partition)).end_offset

    def high_watermark(self, topic: str, partition: int) -> int:
        """The quorum-acked frontier of one partition on the CONNECTED
        broker: what its follower-served ``read_committed`` reads are gated
        on (on a leader / ungated partition this equals the applied end)."""
        return self._invoke("EndOffset", pb.OffsetRequest(
            topic=topic, partition=partition)).high_watermark

    def replication_status(self) -> dict:
        """The connected broker's in-sync set (empty targets on a follower /
        unreplicated broker): {"replicas": {target: in_sync}, "min_insync",
        "insync_count", "queue_depth"} — the Kafka under-replicated-partitions
        operator view."""
        reply = self._invoke("ReplicationStatus",
                             pb.ReplicationStatusRequest())
        return {"replicas": {r.target: r.in_sync for r in reply.replicas},
                "min_insync": reply.min_insync,
                "insync_count": reply.insync_count,
                "queue_depth": reply.queue_depth}

    def latest_by_key(self, topic: str, partition: int,
                      isolation: str = "read_committed") -> Mapping[str, LogRecord]:
        reply = self._invoke("LatestByKey", pb.OffsetRequest(
            topic=topic, partition=partition))
        return {m.key: msg_to_record(m) for m in reply.records}

    # -- broker admin plane ---------------------------------------------------------------

    def broker_status(self) -> dict:
        """The connected broker's role/epoch/leader-hint view (failover
        introspection; the chaos CLI's status command)."""
        import json

        reply = self._invoke("BrokerStatus", pb.ListTopicsRequest())
        if not reply.ok:
            raise RuntimeError(f"BrokerStatus failed: {reply.error}")
        return json.loads(reply.records[0].value)

    def cluster_meta(self, op: str = "status", **payload) -> dict:
        """The connected broker's cluster-metadata plane (ClusterMeta RPC):
        ``status`` reads the membership + partition→leader view; the
        coordinator-only mutations are ``add``/``remove`` (addr=...),
        ``assign`` (partition=..., to=...) and ``spread`` (partitions=N).
        Returns the (new) metadata view."""
        import json

        req = pb.TxnRequest(op=op)
        if payload:
            req.records.append(pb.RecordMsg(
                has_value=True, value=json.dumps(payload).encode()))
        reply = self._invoke("ClusterMeta", req)
        if not reply.ok:
            raise RuntimeError(f"ClusterMeta({op}) failed: {reply.error}")
        return json.loads(reply.records[0].value)

    def add_broker(self, addr: str) -> dict:
        """AddBroker: admit a caught-up broker into the membership (run
        ``catch_up`` on it first; the coordinator refuses a joiner lagging
        past the auto-resync cap)."""
        return self.cluster_meta("add", addr=addr)

    def remove_broker(self, addr: str) -> dict:
        """RemoveBroker: retire a member — its led partitions fail over to
        the surviving members before the membership record shrinks."""
        return self.cluster_meta("remove", addr=addr)

    def promote_follower(self, replicate_to: Optional[Sequence[str]] = None
                         ) -> dict:
        """Promote the CONNECTED broker to leader (admin failover trigger);
        returns its new broker status."""
        import json

        req = pb.TxnRequest(op="promote")
        if replicate_to is not None:
            req.records.append(pb.RecordMsg(has_value=True, value=json.dumps(
                {"replicate_to": list(replicate_to)}).encode()))
        reply = self._invoke("PromoteFollower", req)
        if not reply.ok:
            raise RuntimeError(f"PromoteFollower failed: {reply.error}")
        return json.loads(reply.records[0].value)

    def handoff_partition(self, to: str, timeout: float = 60.0) -> dict:
        """Planned leadership transfer: the CONNECTED broker (must be the
        leader) ships its log to ``to`` as checkpoint-codec slices, fences,
        ships the journal tail + dedup table, promotes ``to`` and demotes
        itself. Returns the handoff stats (bulk/tail records, fence ms,
        handoff epoch). CAVEAT: the unfenced bulk phase scales with how far
        ``to`` is behind — on a DEADLINE_EXCEEDED the server-side handoff
        may still be running AND may still complete; check ``broker_status``
        (or ``chaos.py cluster``) before retrying or killing anything."""
        import json

        req = pb.TxnRequest(op="handoff", records=[pb.RecordMsg(
            has_value=True, value=json.dumps({"to": to}).encode())])
        reply = self._invoke("HandoffPartition", req, timeout=timeout)
        if not reply.ok:
            raise RuntimeError(f"HandoffPartition failed: {reply.error}")
        return json.loads(reply.records[0].value)

    def cluster_handoff(self, to: str, partition: int,
                        timeout: float = 30.0) -> dict:
        """Per-partition planned leadership transfer (spread mode): the
        CONNECTED broker must lead ``partition``; it fences just that index,
        drains it, tail-syncs ``to``, pushes dedup, and flips the assignment
        through the coordinator. Returns the handoff stats."""
        import json

        req = pb.TxnRequest(op="handoff", records=[pb.RecordMsg(
            has_value=True, value=json.dumps(
                {"to": to, "partition": int(partition)}).encode())])
        reply = self._invoke("HandoffPartition", req, timeout=timeout)
        if not reply.ok:
            raise RuntimeError(f"partition handoff failed: {reply.error}")
        return json.loads(reply.records[0].value)

    def kill_broker(self) -> None:
        """Remote hard-stop of the CONNECTED broker (chaos drills: same
        semantics as a fault-plane crash — the socket closes NOW, so the
        reply itself may be lost; unreachable counts as success)."""
        try:
            self._calls["ArmFaults"](pb.TxnRequest(op="kill"), timeout=5.0)
        except grpc.RpcError:
            pass  # the kill raced the reply: that IS the success mode

    def log_metrics_text(self) -> str:
        """The connected broker's OpenMetrics payload (its own registry:
        surge.log.replication.*/journal.*/txn.* + per-follower lag families)
        over the GetMetricsText RPC — scrape-over-gRPC, no scrape port
        needed."""
        reply = self._invoke("GetMetricsText", pb.ListTopicsRequest())
        if not reply.ok:
            raise RuntimeError(f"GetMetricsText failed: {reply.error}")
        return reply.records[0].value.decode()

    def flight_dump(self, last: Optional[int] = None) -> dict:
        """The connected broker's flight-recorder dump (merge-ready envelope,
        surge_tpu.observability.merge_dumps); ``last`` keeps only the newest
        N events (the chaos CLI's tail view)."""
        import json

        req = pb.ReadRequest()
        if last is not None:
            req.has_max = True
            req.max_records = last
        reply = self._invoke("DumpFlight", req)
        if not reply.ok:
            raise RuntimeError(f"DumpFlight failed: {reply.error}")
        return json.loads(reply.records[0].value)

    def trace_dump(self, last: Optional[int] = None) -> dict:
        """The connected broker's tail-kept trace-ring dump (merge-ready
        envelope for surge_tpu.observability.anatomy.assemble_traces);
        ``last`` keeps only the newest N kept traces. Raises RuntimeError on
        an untraced broker (no tracer / tail sampling disabled)."""
        import json

        req = pb.ReadRequest()
        if last is not None:
            req.has_max = True
            req.max_records = last
        reply = self._invoke("DumpTraces", req)
        if not reply.ok:
            raise RuntimeError(f"DumpTraces failed: {reply.error}")
        return json.loads(reply.records[0].value)

    def arm_faults(self, spec: str, seed: int = 0) -> dict:
        """Arm a named fault plan or JSON rule list on the connected broker
        (surge_tpu.testing.faults); returns the plane's stats."""
        return self._faults_op("arm", spec, seed)

    def disarm_faults(self) -> dict:
        return self._faults_op("disarm", "", 0)

    def fault_stats(self) -> dict:
        return self._faults_op("status", "", 0)

    def _faults_op(self, op: str, spec: str, seed: int) -> dict:
        import json

        req = pb.TxnRequest(op=op, txn_seq=seed)
        if spec:
            req.records.append(pb.RecordMsg(has_value=True,
                                            value=spec.encode()))
        reply = self._invoke("ArmFaults", req)
        if not reply.ok:
            raise RuntimeError(f"ArmFaults({op}) failed: {reply.error}")
        return json.loads(reply.records[0].value)

    def partition_digest(self, topic: str, partition: int,
                         upto: Optional[int] = None) -> dict:
        """The connected broker's chained digest over ``[base, upto)`` of one
        partition (surge_tpu.log.digest) — ``upto`` rides ReadRequest's
        ``from_offset`` (None/0 = the broker's durable end). The auditor
        compares leader vs follower digests at the same ``upto`` below the
        hwm without shipping a single record."""
        import json

        reply = self._invoke("PartitionDigest", pb.ReadRequest(
            topic=topic, partition=partition,
            from_offset=0 if upto is None else int(upto)))
        if not reply.ok:
            raise RuntimeError(f"PartitionDigest failed: {reply.error}")
        return json.loads(reply.records[0].value)

    def compact_topic(self, topic: str, partition: int) -> dict:
        """Trigger broker-side compaction of one compacted-topic partition;
        returns the CompactionStats dict. Raises RuntimeError when the broker
        refuses (replicating leader, non-compacted topic)."""
        import json

        reply = self._invoke("CompactTopic", pb.ReadRequest(
            topic=topic, partition=partition))
        if not reply.ok:
            raise RuntimeError(f"CompactTopic failed: {reply.error}")
        return json.loads(reply.records[0].value)

    async def wait_for_append(self, topic: str, partition: int,
                              after_offset: int) -> None:
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            reply = await loop.run_in_executor(None, lambda: self._invoke(
                "WaitForAppend", pb.WaitRequest(
                    topic=topic, partition=partition, after_offset=after_offset,
                    timeout_s=0.5)))
            if reply.appended:
                return
            if loop.time() - t0 < 0.1:
                # the broker's long-poll slots were contended and it answered
                # immediately — pace the retry so this doesn't become a hot loop
                await asyncio.sleep(0.1)

    def close(self) -> None:
        if self._pipeline_pool is not None:
            self._pipeline_pool.shutdown(wait=False)
            self._pipeline_pool = None
        self._channel.close()
