"""GrpcLogTransport — the LogTransport protocol over a remote LogServer.

The KafkaProducer/KafkaConsumer-wrapper role (KafkaProducer.scala:18-265,
KafkaConsumer.scala:17-132): thin, promise-free blocking calls against a remote
broker, with transactions buffered locally and shipped atomically at commit, and
fencing surfaced as :class:`ProducerFencedError`. Calls use a synchronous gRPC
channel — they block the calling thread for one loopback/network round trip, which
is the same envelope the reference's producer calls have against a broker.

``wait_for_append`` long-polls the server from an executor thread so the event loop
stays free (the dedicated poll-thread pattern of KafkaConsumerTrait).
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Dict, List, Mapping, Optional, Sequence

import grpc

from surge_tpu.log import log_service_pb2 as pb
from surge_tpu.log.server import METHODS, SERVICE, msg_to_record, record_to_msg
from surge_tpu.log.transport import (
    LogRecord,
    ProducerFencedError,
    TopicSpec,
    TransactionStateError,
)


def _raise_for(reply: pb.TxnReply) -> None:
    if reply.ok:
        return
    if reply.error_kind == "fenced":
        raise ProducerFencedError(reply.error)
    if reply.error_kind == "state":
        raise TransactionStateError(reply.error)
    raise RuntimeError(f"log server error: {reply.error}")


class GrpcTxnProducer:
    """Client half of a server-side transactional producer (one token).

    Commits are idempotent over the wire: every commit/send_immediate carries a
    per-token sequence number, and a lost reply is retried with the SAME number —
    the server answers a replayed sequence from its cached reply instead of
    appending the transaction twice (the Kafka idempotent-producer role,
    KafkaProducerActorImpl.scala:161-165 `enable.idempotence`).
    """

    def __init__(self, transport: "GrpcLogTransport", token: int) -> None:
        self._transport = transport
        self._token = token
        self._buffer: Optional[List[LogRecord]] = None
        self._fenced = False
        self._next_seq = 1

    @property
    def fenced(self) -> bool:
        """Whether this producer has observed itself fenced.

        Lazy, unlike InMemoryTxnProducer: it flips only after an operation
        fails with ``error_kind="fenced"`` — a proactive poll can read a stale
        False until the next wire operation. The publisher FSM only consults it
        after a failed publish, where the two contracts agree; callers needing
        a fresh answer should attempt an operation rather than poll this.
        """
        return self._fenced

    @property
    def in_transaction(self) -> bool:
        return self._buffer is not None

    def begin(self) -> None:
        if self._buffer is not None:
            raise TransactionStateError("transaction already open")
        self._buffer = []

    def send(self, record: LogRecord) -> None:
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        self._buffer.append(record)

    def commit(self) -> Sequence[LogRecord]:
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        records, self._buffer = self._buffer, None
        reply = self._transport._transact(self._token, "commit", records,
                                          seq=self._next_seq)
        self._check_fence(reply)
        _raise_for(reply)
        self._next_seq += 1
        return [msg_to_record(m) for m in reply.records]

    def abort(self) -> None:
        if self._buffer is None:
            raise TransactionStateError("no open transaction")
        self._buffer = None  # records never left this process

    def send_immediate(self, record: LogRecord) -> LogRecord:
        reply = self._transport._transact(self._token, "send_immediate",
                                          [record], seq=self._next_seq)
        self._check_fence(reply)
        _raise_for(reply)
        self._next_seq += 1
        return msg_to_record(reply.records[0])

    def _check_fence(self, reply: pb.TxnReply) -> None:
        if not reply.ok and reply.error_kind == "fenced":
            self._fenced = True


class GrpcLogTransport:
    """:class:`surge_tpu.log.transport.LogTransport` against a remote LogServer."""

    def __init__(self, target: str, config=None,
                 auto_create_partitions: int = 1) -> None:
        from surge_tpu.remote.security import secure_sync_channel

        self.target = target
        self._channel = secure_sync_channel(target, config)
        self._calls: Dict[str, object] = {}
        for name, (req_cls, reply_cls) in METHODS.items():
            self._calls[name] = self._channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=reply_cls.FromString)
        self._auto_create_partitions = auto_create_partitions
        self._topics: Dict[str, TopicSpec] = {}  # local spec cache
        self._lock = threading.Lock()

    # -- topics ---------------------------------------------------------------------------

    def create_topic(self, spec: TopicSpec) -> None:
        self._calls["CreateTopic"](pb.CreateTopicRequest(spec=pb.TopicSpecMsg(
            name=spec.name, partitions=spec.partitions, compacted=spec.compacted)))
        with self._lock:
            self._topics[spec.name] = spec

    def topic(self, name: str) -> TopicSpec:
        with self._lock:
            hit = self._topics.get(name)
        if hit is not None:
            return hit
        reply = self._calls["GetTopic"](pb.TopicRequest(name=name))
        if not reply.found:
            # parity with InMemoryLog: unknown topics auto-create
            spec = TopicSpec(name, self._auto_create_partitions)
            self.create_topic(spec)
            return spec
        spec = TopicSpec(reply.spec.name, reply.spec.partitions, reply.spec.compacted)
        with self._lock:
            self._topics[name] = spec
        return spec

    def num_partitions(self, name: str) -> int:
        return self.topic(name).partitions

    # -- producers ------------------------------------------------------------------------

    def transactional_producer(self, transactional_id: str) -> GrpcTxnProducer:
        reply = self._calls["OpenProducer"](
            pb.OpenProducerRequest(transactional_id=transactional_id))
        return GrpcTxnProducer(self, reply.producer_token)

    def _transact(self, token: int, op: str, records: Sequence[LogRecord],
                  seq: int = 0, attempts: int = 4) -> pb.TxnReply:
        request = pb.TxnRequest(
            producer_token=token, op=op, txn_seq=seq,
            records=[record_to_msg(r) for r in records])
        backoff = 0.05
        for attempt in range(attempts):
            try:
                return self._calls["Transact"](request)
            except grpc.RpcError as exc:
                # Reply loss / transient broker unavailability: retry the SAME
                # txn_seq so a commit the server did apply is answered from its
                # dedup cache, not appended again. Anything non-transient (or
                # seq-less ops, which we cannot safely replay) propagates.
                code = exc.code() if hasattr(exc, "code") else None
                transient = code in (grpc.StatusCode.UNAVAILABLE,
                                     grpc.StatusCode.DEADLINE_EXCEEDED)
                if not seq or not transient or attempt == attempts - 1:
                    raise
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.4)
        raise RuntimeError("unreachable")

    # -- reads ----------------------------------------------------------------------------

    def read(self, topic: str, partition: int, from_offset: int = 0,
             max_records: Optional[int] = None,
             isolation: str = "read_committed") -> Sequence[LogRecord]:
        del isolation  # the server's log already serves committed records only
        req = pb.ReadRequest(topic=topic, partition=partition,
                             from_offset=from_offset)
        if max_records is not None:
            req.has_max = True
            req.max_records = max_records
        reply = self._calls["Read"](req)
        return [msg_to_record(m) for m in reply.records]

    def end_offset(self, topic: str, partition: int,
                   isolation: str = "read_committed") -> int:
        del isolation
        self.topic(topic)  # auto-create parity
        return self._calls["EndOffset"](
            pb.OffsetRequest(topic=topic, partition=partition)).end_offset

    def latest_by_key(self, topic: str, partition: int,
                      isolation: str = "read_committed") -> Mapping[str, LogRecord]:
        reply = self._calls["LatestByKey"](
            pb.OffsetRequest(topic=topic, partition=partition))
        return {m.key: msg_to_record(m) for m in reply.records}

    async def wait_for_append(self, topic: str, partition: int,
                              after_offset: int) -> None:
        loop = asyncio.get_running_loop()
        while True:
            t0 = loop.time()
            reply = await loop.run_in_executor(None, lambda: self._calls[
                "WaitForAppend"](pb.WaitRequest(
                    topic=topic, partition=partition, after_offset=after_offset,
                    timeout_s=0.5)))
            if reply.appended:
                return
            if loop.time() - t0 < 0.1:
                # the broker's long-poll slots were contended and it answered
                # immediately — pace the retry so this doesn't become a hot loop
                await asyncio.sleep(0.1)

    def close(self) -> None:
        self._channel.close()
