"""Columnar event-log segments: the bulk-replay storage format.

SURVEY.md §7 hard-part 3: folding a 100M-event topic cannot afford per-event Python
objects — the reference's restore path (Kafka Streams changelog scan) streams record
batches; the TPU-native equivalent streams **struct-of-arrays chunks** straight into
:meth:`surge_tpu.replay.ReplayEngine.replay_columnar`. This module is the durable
form of :class:`~surge_tpu.codec.tensor.ColumnarEvents`:

- A **segment file** holds a header (schema: columns, dtypes, derived-column
  declarations) and a sequence of chunks. Each chunk covers a disjoint, contiguous
  range of aggregates (aggregate-sorted), so chunks replay independently and their
  state columns concatenate.
- Column bytes are SLZ-compressed per column (csrc/segment.cc) when the native codec
  is built — event streams compress well (narrow dtypes, repeated patterns).
- ``build_segment_from_topic`` is the offline conversion job: read an events topic
  through the app's event format once, encode columnar, write the segment. Replays
  after that never touch Python objects again (the role of Kafka's compacted-restore
  optimization, performed once instead of per cold start).

Layout (little-endian):
    magic "SCOL" | u32 header_len | header JSON |
    per section: u32 marker | u32 meta_len | meta JSON | payloads
    - chunk section (marker "CHK1"): column payloads in meta order (raw or SLZ per
      meta); meta may also carry an "ids" payload (newline-joined aggregate-id
      strings) so replay can write folded states back to the keyed store
    - snapshot section (marker "SNP1"): one uvarint-framed key/value blob holding
      the latest state snapshots of aggregates ABSENT from the events topic
      (state-only publishes) — the checkpoint-carry that lets a segment restore
      skip the post-replay state-topic scan entirely
Header JSON: {"columns": {name: dtype_str}, "derived": {...}, "type_dtype": str,
              "extra": {...}} — "extra" carries build-time metadata such as the
source topic watermarks (see build_segment_from_topic).
Chunk meta JSON: {"num_aggregates": n, "num_events": m,
                  "cols": [[name, codec, stored_len, raw_len], ...],
                  "ids": [codec, stored_len, raw_len] | absent}  — cols includes the
implicit "agg_idx" and "type_ids" columns.
"""

from __future__ import annotations

import json
import struct
from typing import Iterator, Optional

import numpy as np

from surge_tpu.codec.tensor import ColumnarEvents
from surge_tpu.log import segment as seg

MAGIC = b"SCOL"
CHUNK_MARKER = 0x43484B31
SNAPSHOT_MARKER = 0x534E5031  # "SNP1"
WATERMARK_MARKER = 0x574D4B31  # "WMK1" — extend-time watermark override (no payload)
EXTEND_MARKER = 0x45585442  # "EXTB" — length-framed extend batch (crash guard)


def _encode_array(arr: np.ndarray):
    raw = np.ascontiguousarray(arr).tobytes()
    compressed = seg.slz_compress(raw)
    if compressed is not None:
        return seg.CODEC_SLZ, compressed, len(raw)
    return seg.CODEC_RAW, raw, len(raw)


def _decode_array(data: bytes, codec: int, raw_len: int, dtype: np.dtype) -> np.ndarray:
    if codec == seg.CODEC_SLZ:
        data = seg.slz_decompress(data, raw_len)
    return np.frombuffer(data, dtype=dtype)


class ColumnarSegmentWriter:
    """Appends aggregate-range chunks of a model family's event log."""

    def __init__(self, path: str, extra_header: Optional[dict] = None) -> None:
        self.path = path
        self._file = None
        self._header_written = False
        self._schema: Optional[dict] = None
        self._extra = dict(extra_header or {})
        self._total_aggregates = 0
        self._total_events = 0
        self._extend_target: Optional[str] = None

    @classmethod
    def extend(cls, path: str) -> "ColumnarSegmentWriter":
        """Open an EXISTING segment for appending delta sections (incremental
        maintenance, SURVEY.md §5.4 compaction-as-checkpoint role). The header
        stays immutable; updated watermarks ride a WMK section (see
        :meth:`write_watermarks`) and chunks whose schema diverges from the
        header (e.g. delta chunks storing a column the base derives) carry
        per-chunk overrides in their meta.

        Crash safety: delta sections are staged in memory and appended on
        ``close()`` as ONE length-framed EXTB super-section (fsync'd). Readers
        validate the frame length, so a torn append is ignored wholesale — the
        segment is always either pre- or post-extend, never half."""
        import io

        with open(path, "rb") as f:
            head = f.read(8)
            if head[:4] != MAGIC:
                raise ValueError(f"{path}: not a columnar segment")
            (hlen,) = struct.unpack("<I", head[4:8])
            schema = json.loads(f.read(hlen))
        w = cls(path, extra_header=schema.get("extra"))
        w._schema = schema
        w._file = io.BytesIO()
        w._extend_target = path
        return w

    def write_watermarks(self, watermarks: dict,
                         state_watermarks: Optional[dict] = None) -> None:
        """Append a watermark-override section: readers treat the LAST one as
        authoritative over the header's build-time extra."""
        if self._file is None:
            raise ValueError("no open segment")
        meta_obj: dict = {"watermarks": {str(k): int(v)
                                         for k, v in watermarks.items()}}
        if state_watermarks is not None:
            meta_obj["state_watermarks"] = {str(k): int(v)
                                            for k, v in state_watermarks.items()}
        meta = json.dumps(meta_obj).encode()
        self._file.write(struct.pack("<II", WATERMARK_MARKER, len(meta)) + meta)

    def _write_header(self, schema: dict) -> None:
        # Fresh segment at this path: stamp a per-build identity into the
        # header (restore's sidecar wire cache keys on it — a rebuilt segment
        # whose chunk happens to share an ordinal+event-count with the old
        # build must never hit the old build's cached wires, ADVICE r4) and
        # drop any leftover sidecar cache from a previous build outright.
        # extend() never lands here, so extends keep the base build's id —
        # correct, since extends only APPEND chunks at new ordinals.
        import shutil
        import uuid

        self._extra.setdefault("build_id", uuid.uuid4().hex)
        shutil.rmtree(f"{self.path}.wires", ignore_errors=True)
        self._file = open(self.path, "wb")
        header = json.dumps(schema).encode()
        self._file.write(MAGIC + struct.pack("<I", len(header)) + header)
        self._schema = schema

    def append(self, colev: ColumnarEvents,
               partition: Optional[int] = None) -> None:
        """Append one chunk. Every chunk must share the first chunk's column schema;
        each holds its own disjoint aggregate range (ids are chunk-local 0..n).
        ``colev.aggregate_ids`` (if set) is persisted alongside the columns.
        ``partition`` records which source partition the chunk's aggregates belong
        to, enabling partition-scoped restore (SURVEY.md §3.3 per-task restore)."""
        colev = colev.sorted_by_aggregate()
        schema = {
            "columns": {name: str(col.dtype) for name, col in sorted(colev.cols.items())},
            "derived": dict(colev.derived_cols),
            "type_dtype": str(colev.type_ids.dtype),
            "agg_dtype": str(colev.agg_idx.dtype),
            "extra": self._extra,
        }
        overrides: dict = {}
        if self._file is None:
            self._write_header(schema)
        elif schema != self._schema:
            # a chunk may diverge from the header schema (delta chunks STORE a
            # column the base chunks derive on-device, since their events'
            # ordinals are absolute, not 1-based): persist per-chunk overrides
            # the reader prefers over the header
            overrides = {"dtypes": schema["columns"],
                         "chunk_derived": schema["derived"],
                         "type_dtype": schema["type_dtype"],
                         "agg_dtype": schema["agg_dtype"]}

        cols_meta = []
        payloads = []
        for name, arr in [("agg_idx", colev.agg_idx), ("type_ids", colev.type_ids)] + \
                sorted(colev.cols.items()):
            codec, stored, raw_len = _encode_array(arr)
            cols_meta.append([name, codec, len(stored), raw_len])
            payloads.append(stored)
        meta_obj = {
            "num_aggregates": colev.num_aggregates,
            "num_events": colev.num_events,
            "cols": cols_meta,
            **overrides,
        }
        if partition is not None:
            meta_obj["partition"] = int(partition)
        if colev.aggregate_ids is not None:
            if len(colev.aggregate_ids) != colev.num_aggregates:
                raise ValueError("aggregate_ids length != num_aggregates")
            if any("\n" in i or not i for i in colev.aggregate_ids):
                raise ValueError("aggregate ids must be non-empty and newline-free "
                                 "(newline is the id separator)")
            raw = "\n".join(colev.aggregate_ids).encode()
            compressed = seg.slz_compress(raw)
            if compressed is not None:
                meta_obj["ids"] = [seg.CODEC_SLZ, len(compressed), len(raw)]
                payloads.append(compressed)
            else:
                meta_obj["ids"] = [seg.CODEC_RAW, len(raw), len(raw)]
                payloads.append(raw)
        meta = json.dumps(meta_obj).encode()
        self._file.write(struct.pack("<II", CHUNK_MARKER, len(meta)) + meta)
        for p in payloads:
            self._file.write(p)
        self._total_aggregates += colev.num_aggregates
        self._total_events += colev.num_events

    def append_snapshots(self, items, partition: Optional[int] = None) -> None:
        """Write a snapshot section: latest serialized states of aggregates the
        events topic does not cover (state-only publishes). ``items`` is an
        iterable of ``(key: str, value: bytes)``; ``partition`` scopes the section
        to one source state partition for partition-scoped restore."""
        if self._file is None:
            raise ValueError("append at least one chunk before snapshots")
        blob = bytearray()
        count = 0
        for key, value in items:
            kb = key.encode()
            seg._put_uvarint(blob, len(kb))
            blob += kb
            seg._put_uvarint(blob, len(value))
            blob += value
            count += 1
        raw = bytes(blob)
        compressed = seg.slz_compress(raw)
        if compressed is not None:
            meta_obj = {"count": count, "blob": [seg.CODEC_SLZ, len(compressed), len(raw)]}
            payload = compressed
        else:
            meta_obj = {"count": count, "blob": [seg.CODEC_RAW, len(raw), len(raw)]}
            payload = raw
        if partition is not None:
            meta_obj["partition"] = int(partition)
        meta = json.dumps(meta_obj).encode()
        self._file.write(struct.pack("<II", SNAPSHOT_MARKER, len(meta)) + meta)
        self._file.write(payload)

    def close(self) -> None:
        if self._file is None:
            return
        if self._extend_target is not None:
            import os

            blob = self._file.getvalue()
            self._file = None
            if blob:
                frame = struct.pack("<II", EXTEND_MARKER, len(blob))
                with open(self._extend_target, "ab") as f:
                    f.write(frame + blob)
                    f.flush()
                    os.fsync(f.fileno())
            return
        self._file.flush()
        self._file.close()
        self._file = None

    def __enter__(self) -> "ColumnarSegmentWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_segment(path: str,
                 partitions: Optional[set] = None,
                 columns: Optional[Iterable[str]] = None
                 ) -> Iterator[ColumnarEvents]:
    """Stream the segment's chunks back as ColumnarEvents (zero-copy frombuffer
    views over the decompressed column bytes). ``partitions`` keeps only chunks
    whose recorded source partition is in the set — chunks without partition
    metadata (pre-scoping segments) always pass, and their payloads are seeked
    past, not decompressed, when filtered out.

    ``columns`` is the query engine's projection pushdown: when given, only
    those union columns (plus the structural ``agg_idx``/``type_ids`` and the
    id payload) are decompressed — every other column payload is seeked past.
    The yielded chunks then carry exactly the projected ``cols``; callers that
    need the full schema must not pass ``columns``."""
    import os as _os

    if partitions is not None:
        partitions = {int(p) for p in partitions}
    wanted = None if columns is None else set(columns)
    with open(path, "rb") as f:
        size = _os.fstat(f.fileno()).st_size
        head = f.read(8)
        if head[:4] != MAGIC:
            raise ValueError(f"{path}: not a columnar segment")
        (hlen,) = struct.unpack("<I", head[4:8])
        header = json.loads(f.read(hlen))
        col_dtypes = {name: np.dtype(dt) for name, dt in header["columns"].items()}
        type_dtype = np.dtype(header["type_dtype"])
        agg_dtype = np.dtype(header["agg_dtype"])
        derived = dict(header.get("derived", {}))

        ordinal = -1  # global chunk ordinal (counts filtered chunks too)
        while True:
            prefix = f.read(8)
            if len(prefix) < 8:
                return  # end of file (or torn final append)
            marker, mlen = struct.unpack("<II", prefix)
            if marker == EXTEND_MARKER:
                if size - f.tell() < mlen:
                    return  # torn extend append: ignore wholesale (crash guard)
                continue  # validated: inner sections follow normally
            if marker not in (CHUNK_MARKER, SNAPSHOT_MARKER, WATERMARK_MARKER):
                raise ValueError(f"{path}: bad section marker {marker:#x}")
            meta = json.loads(f.read(mlen))
            if marker == WATERMARK_MARKER:  # no payload; segment_info reads it
                continue
            if marker == SNAPSHOT_MARKER:  # not a chunk; read via read_segment_snapshots
                f.seek(meta["blob"][1], 1)
                continue
            ordinal += 1
            if (partitions is not None and "partition" in meta
                    and meta["partition"] not in partitions):
                skip = sum(c[2] for c in meta["cols"])
                if "ids" in meta:
                    skip += meta["ids"][1]
                f.seek(skip, 1)
                continue
            # per-chunk schema overrides (delta chunks may store a column the
            # header declares derived)
            c_cols = ({n: np.dtype(d) for n, d in meta["dtypes"].items()}
                      if "dtypes" in meta else col_dtypes)
            c_type = np.dtype(meta["type_dtype"]) if "type_dtype" in meta else type_dtype
            c_agg = np.dtype(meta["agg_dtype"]) if "agg_dtype" in meta else agg_dtype
            c_derived = (dict(meta["chunk_derived"]) if "chunk_derived" in meta
                         else dict(derived))
            arrays = {}
            for name, codec, stored_len, raw_len in meta["cols"]:
                if (wanted is not None
                        and name not in ("agg_idx", "type_ids")
                        and name not in wanted):
                    f.seek(stored_len, 1)  # projected out: never decompressed
                    continue
                dtype = (c_agg if name == "agg_idx"
                         else c_type if name == "type_ids"
                         else c_cols[name])
                arrays[name] = _decode_array(f.read(stored_len), codec, raw_len, dtype)
            ids = None
            if "ids" in meta:
                codec, stored_len, raw_len = meta["ids"]
                raw = f.read(stored_len)
                if codec == seg.CODEC_SLZ:
                    raw = seg.slz_decompress(raw, raw_len)
                ids = raw.decode().split("\n") if raw else []
                if len(ids) != meta["num_aggregates"]:
                    raise ValueError(
                        f"{path}: id count {len(ids)} != aggregates "
                        f"{meta['num_aggregates']} — corrupt chunk")
            yield ColumnarEvents(
                num_aggregates=meta["num_aggregates"],
                agg_idx=arrays.pop("agg_idx"),
                type_ids=arrays.pop("type_ids"),
                cols=arrays,
                derived_cols=c_derived,
                aggregate_ids=ids,
                source_ordinal=ordinal)


def segment_info(path: str) -> dict:
    """Totals + schema without decompressing column payloads. The schema's
    ``extra`` watermarks reflect the LAST watermark-override section, so an
    incrementally extended segment reports its post-extend coverage."""
    import os as _os

    total_aggregates = total_events = num_chunks = num_snapshots = 0
    num_extends = 0
    with open(path, "rb") as f:
        size = _os.fstat(f.fileno()).st_size
        head = f.read(8)
        if head[:4] != MAGIC:
            raise ValueError(f"{path}: not a columnar segment")
        (hlen,) = struct.unpack("<I", head[4:8])
        header = json.loads(f.read(hlen))
        while True:
            prefix = f.read(8)
            if len(prefix) < 8:
                break  # end of file (or torn final append)
            marker, mlen = struct.unpack("<II", prefix)
            if marker == EXTEND_MARKER:
                if size - f.tell() < mlen:
                    break  # torn extend append: ignore wholesale
                num_extends += 1
                continue
            if marker not in (CHUNK_MARKER, SNAPSHOT_MARKER, WATERMARK_MARKER):
                raise ValueError(f"{path}: bad section marker {marker:#x}")
            meta = json.loads(f.read(mlen))
            if marker == WATERMARK_MARKER:
                header.setdefault("extra", {}).update(meta)
                continue
            if marker == SNAPSHOT_MARKER:
                f.seek(meta["blob"][1], 1)
                num_snapshots += meta["count"]
                continue
            skip = sum(c[2] for c in meta["cols"])
            if "ids" in meta:
                skip += meta["ids"][1]
            f.seek(skip, 1)
            total_aggregates += meta["num_aggregates"]
            total_events += meta["num_events"]
            num_chunks += 1
    return {"schema": header, "num_aggregates": total_aggregates,
            "num_events": total_events, "num_chunks": num_chunks,
            "num_snapshots": num_snapshots, "num_extends": num_extends}


def read_segment_snapshots(path: str,
                           partitions: Optional[set] = None) -> Iterator[tuple]:
    """Stream the snapshot sections' ``(key, value)`` pairs (state-only
    aggregates). ``partitions`` keeps only sections recorded for those source
    state partitions (sections without partition metadata always pass)."""
    import os as _os

    if partitions is not None:
        partitions = {int(p) for p in partitions}
    with open(path, "rb") as f:
        size = _os.fstat(f.fileno()).st_size
        head = f.read(8)
        if head[:4] != MAGIC:
            raise ValueError(f"{path}: not a columnar segment")
        (hlen,) = struct.unpack("<I", head[4:8])
        f.seek(hlen, 1)
        while True:
            prefix = f.read(8)
            if len(prefix) < 8:
                return  # end of file (or torn final append)
            marker, mlen = struct.unpack("<II", prefix)
            if marker == EXTEND_MARKER:
                if size - f.tell() < mlen:
                    return  # torn extend append: ignore wholesale
                continue
            if marker not in (CHUNK_MARKER, SNAPSHOT_MARKER, WATERMARK_MARKER):
                raise ValueError(f"{path}: bad section marker {marker:#x}")
            meta = json.loads(f.read(mlen))
            if marker == WATERMARK_MARKER:
                continue
            if marker != SNAPSHOT_MARKER:
                skip = sum(c[2] for c in meta["cols"])
                if "ids" in meta:
                    skip += meta["ids"][1]
                f.seek(skip, 1)
                continue
            if (partitions is not None and "partition" in meta
                    and meta["partition"] not in partitions):
                f.seek(meta["blob"][1], 1)
                continue
            codec, stored_len, raw_len = meta["blob"]
            raw = f.read(stored_len)
            if codec == seg.CODEC_SLZ:
                raw = seg.slz_decompress(raw, raw_len)
            pos = 0
            for _ in range(meta["count"]):
                klen, pos = seg._get_uvarint(raw, pos)
                key = raw[pos: pos + klen].decode()
                pos += klen
                vlen, pos = seg._get_uvarint(raw, pos)
                value = raw[pos: pos + vlen]
                pos += vlen
                yield key, value


def _drop_derived(colev: ColumnarEvents, derived_cols: dict) -> None:
    """Remove columns the device will re-derive — after VERIFYING the data really
    matches the derivation (an ordinal declaration over a column whose values are
    not positional would silently corrupt the replay)."""
    n = colev.num_events
    if n:
        starts = np.zeros(colev.num_aggregates + 1, dtype=np.int64)
        np.cumsum(np.bincount(colev.agg_idx, minlength=colev.num_aggregates),
                  out=starts[1:])
        ordinal = np.arange(n, dtype=np.int64) - starts[colev.agg_idx] + 1
    for name, kind in derived_cols.items():
        col = colev.cols.get(name)
        if col is not None:
            if kind == "ordinal" and n and not np.array_equal(
                    col.astype(np.int64), ordinal):
                raise ValueError(
                    f"column {name!r} declared derived as ordinal but its values "
                    f"are not positional — refusing to drop it")
            del colev.cols[name]
        colev.derived_cols[name] = kind


def build_segment_from_topic(log, topic: str, registry, deserialize_event,
                             path: str, partitions=None,
                             encode_event=None,
                             derived_cols: Optional[dict] = None,
                             chunk_aggregates: int = 65536,
                             state_topic: Optional[str] = None) -> dict:
    """Offline conversion job: events topic → columnar segment.

    Reads every partition's records once, groups events per aggregate (key),
    encodes them columnar via the registry, and writes aggregate-range chunks
    with their aggregate ids. ``encode_event`` maps raw events to tensor-schema
    form first (e.g. vocab dictionary encoding). Returns ``segment_info(path)``.

    The header's ``extra`` records the source watermarks at build time so a
    restore can prime the indexer exactly where the segment's coverage ends.
    When ``state_topic`` is given, the latest snapshots of aggregates ABSENT
    from the events topic (state-only publishes) are carried in a snapshot
    section, making the segment a complete cold-start image — the restore needs
    no state-topic scan (the Kafka Streams restore equivalent,
    AggregateStateStoreKafkaStreams.scala:53-178, performed once at build).
    """
    import os
    import shutil
    import tempfile

    from surge_tpu.codec.tensor import encode_events_columnar
    from surge_tpu.serialization import SerializedMessage

    from surge_tpu.log.transport import page_keyed_records

    if partitions is None:
        partitions = range(log.num_partitions(topic))
    partitions = list(partitions)

    # Watermarks are captured FIRST and every pass is clamped to them: on a
    # LIVE topic, records committed mid-build would otherwise be seen by the
    # spill pass but not the key census (KeyError on a brand-new key) or be
    # folded despite lying past the recorded watermark (double-applied when
    # the indexer resumes there). Clamping gives the build one consistent
    # snapshot; later records belong to the tailing indexer / a later extend.
    wm_int = {p: log.end_offset(topic, p) for p in partitions}
    watermarks = {str(p): off for p, off in wm_int.items()}

    def scan(p: int):
        """Paged snapshot scan (restore-consumer-max-poll-records role,
        common reference.conf:198-199) — a 100M-event topic never
        materializes as one Python list."""
        return page_keyed_records(log, topic, p, upto=wm_int[p])

    # Pass 1: key census only (key → source partition) — O(num_aggregates)
    # memory, no event objects.
    key_partition: dict[str, int] = {}
    for p in partitions:
        for r in scan(p):
            key_partition[r.key] = p
    # chunks are PER PARTITION (sorted keys within each) so a node can restore
    # only its assigned partitions' chunks (SURVEY.md §3.3 per-task restore)
    ordered: list[str] = []
    chunk_plan: list[tuple[int, list[str]]] = []  # (partition, keys)
    for p in partitions:
        keys_p = sorted(k for k, kp in key_partition.items() if kp == p)
        ordered.extend(keys_p)
        for start in range(0, len(keys_p), chunk_aggregates):
            chunk_plan.append((p, keys_p[start: start + chunk_aggregates]))
    chunk_of = {k: i for i, (_, ks) in enumerate(chunk_plan) for k in ks}
    num_chunks = len(chunk_plan)

    extra: dict = {"topic": topic, "watermarks": watermarks}
    snapshots_by_partition: dict[int, list[tuple]] = {}
    if state_topic is not None:
        state_watermarks: dict[str, int] = {}
        for p in range(log.num_partitions(state_topic)):
            for key, rec in log.latest_by_key(state_topic, p).items():
                if key not in key_partition and rec.value:
                    snapshots_by_partition.setdefault(p, []).append((key, rec.value))
            state_watermarks[str(p)] = log.end_offset(state_topic, p)
        extra["state_topic"] = state_topic
        extra["state_watermarks"] = state_watermarks

    # Pass 2: spill each record's raw bytes into its chunk-range file, then
    # encode one chunk at a time — peak footprint is ONE chunk's events plus the
    # key census, not the whole corpus (advisor r3 finding #4). Per-key event
    # order is preserved: a key lives in one partition and each partition is
    # scanned in offset order.
    spill_dir = tempfile.mkdtemp(prefix=".scol-build-",
                                 dir=os.path.dirname(path) or ".")
    try:
        spills = [open(os.path.join(spill_dir, f"c{i}"), "wb", buffering=1 << 20)
                  for i in range(num_chunks)]
        try:
            for p in partitions:
                for r in scan(p):
                    kb = r.key.encode()
                    frame = bytearray()
                    seg._put_uvarint(frame, len(kb))
                    frame += kb
                    seg._put_uvarint(frame, len(r.value))
                    frame += r.value
                    spills[chunk_of[r.key]].write(frame)
        finally:
            for f in spills:
                f.close()

        def chunk_events(i: int, chunk_ids: list) -> list:
            with open(os.path.join(spill_dir, f"c{i}"), "rb") as f:
                data = f.read()
            by_key: dict[str, list] = {k: [] for k in chunk_ids}
            pos = 0
            while pos < len(data):
                klen, pos = seg._get_uvarint(data, pos)
                key = data[pos: pos + klen].decode()
                pos += klen
                vlen, pos = seg._get_uvarint(data, pos)
                ev = deserialize_event(SerializedMessage(
                    key=key, value=data[pos: pos + vlen]))
                pos += vlen
                if encode_event is not None:
                    ev = encode_event(ev)
                by_key[key].append(ev)
            return [by_key[a] for a in chunk_ids]

        with ColumnarSegmentWriter(path, extra_header=extra) as writer:
            if not chunk_plan:  # empty topic: one empty schema-bearing chunk
                colev = encode_events_columnar(registry, [])
                if derived_cols:
                    _drop_derived(colev, derived_cols)
                colev.aggregate_ids = []
                writer.append(colev)
            for i, (p, chunk_ids) in enumerate(chunk_plan):
                colev = encode_events_columnar(registry, chunk_events(i, chunk_ids))
                if derived_cols:
                    _drop_derived(colev, derived_cols)
                colev.aggregate_ids = list(chunk_ids)
                writer.append(colev, partition=p)
            for p in sorted(snapshots_by_partition):
                writer.append_snapshots(snapshots_by_partition[p], partition=p)
    finally:
        shutil.rmtree(spill_dir, ignore_errors=True)
    return {"aggregate_order": ordered, **segment_info(path)}


def extend_segment_from_topic(log, topic: str, registry, deserialize_event,
                              path: str, encode_event=None,
                              chunk_aggregates: int = 65536,
                              state_topic: Optional[str] = None) -> dict:
    """Incremental segment maintenance (VERDICT r3 next #8): append DELTA chunks
    covering events between the segment's recorded watermarks and the topic's
    current end, plus a snapshot section for aggregates whose post-build changes
    were state-only, then a watermark-override section. A later cold start
    restores from segment + delta without any full rebuild; no-op (and cheap)
    when nothing new exists.

    Delta chunks do NOT declare derived columns: their events' ordinals are
    absolute continuations, so positional columns are stored explicitly (the
    chunk meta carries the schema override) and the restore continues each
    aggregate's fold from its already-restored state via ``init_carry``.
    """
    from surge_tpu.codec.tensor import encode_events_columnar
    from surge_tpu.serialization import SerializedMessage

    info = segment_info(path)
    extra = info["schema"].get("extra", {})
    base_wm = {int(p): int(off)
               for p, off in (extra.get("watermarks") or {}).items()}
    partitions = sorted(base_wm) if base_wm else list(
        range(log.num_partitions(topic)))

    # collect the delta per partition (small by construction: post-build only);
    # the new watermark is captured BEFORE the scan and clamps it, so a live
    # producer's mid-extend commits wait for the NEXT extend instead of being
    # folded past the recorded frontier (same snapshot discipline as the build)
    from surge_tpu.log.transport import page_keyed_records

    delta: dict[int, dict[str, list]] = {}
    new_wm: dict[str, int] = {}
    delta_keys: set[str] = set()
    for p in partitions:
        new_wm[str(p)] = log.end_offset(topic, p)
        per_key: dict[str, list] = {}
        for r in page_keyed_records(log, topic, p, start=base_wm.get(p, 0),
                                    upto=int(new_wm[str(p)])):
            ev = deserialize_event(SerializedMessage(key=r.key, value=r.value))
            if encode_event is not None:
                ev = encode_event(ev)
            per_key.setdefault(r.key, []).append(ev)
            delta_keys.add(r.key)
        if per_key:
            delta[p] = per_key

    state_wm: Optional[dict] = None
    snapshots_by_partition: dict[int, list[tuple]] = {}
    if state_topic is not None:
        base_state_wm = {int(p): int(off) for p, off in
                         (extra.get("state_watermarks") or {}).items()}
        state_wm = {}
        for p in range(log.num_partitions(state_topic)):
            # aggregates changed in the delta window WITHOUT delta events
            # (state-only publishes): carry their newest snapshot
            window_keys: set = set()
            offset = base_state_wm.get(p, 0)
            while True:
                batch = log.read(state_topic, p, from_offset=offset,
                                 max_records=10_000)
                if not batch:
                    break
                window_keys.update(r.key for r in batch
                                   if r.key is not None
                                   and r.key not in delta_keys)
                offset = batch[-1].offset + 1
            if window_keys:
                latest = log.latest_by_key(state_topic, p)
                items = [(k, latest[k].value) for k in sorted(window_keys)
                         if k in latest and latest[k].value]
                if items:
                    snapshots_by_partition[p] = items
            state_wm[str(p)] = log.end_offset(state_topic, p)

    if not delta and not snapshots_by_partition:
        return info  # nothing new since the last build/extend

    # a key living only in snapshot sections has no chunk state to continue a
    # fold from — its delta goes in as a fresh snapshot, not an event chunk
    snapshot_keys = {k for k, _ in read_segment_snapshots(path)}
    with ColumnarSegmentWriter.extend(path) as writer:
        for p in sorted(delta):
            keys = sorted(k for k in delta[p] if k not in snapshot_keys)
            demoted = sorted(k for k in delta[p] if k in snapshot_keys)
            if demoted and state_topic is not None:
                latest = log.latest_by_key(state_topic, p)
                snapshots_by_partition.setdefault(p, []).extend(
                    (k, latest[k].value) for k in demoted
                    if k in latest and latest[k].value)
            for start in range(0, len(keys), chunk_aggregates):
                chunk_ids = keys[start: start + chunk_aggregates]
                colev = encode_events_columnar(
                    registry, [delta[p][k] for k in chunk_ids])
                colev.aggregate_ids = list(chunk_ids)
                writer.append(colev, partition=p)
        for p in sorted(snapshots_by_partition):
            writer.append_snapshots(snapshots_by_partition[p], partition=p)
        writer.write_watermarks(new_wm, state_wm)
    return segment_info(path)
