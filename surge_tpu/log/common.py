"""Lazy record views — the native reply/read legs' LogRecord twins.

PR 10's paired ladder showed the remaining broker-path wall is the Python
wrapped AROUND the native core: the read and reply legs built one frozen
dataclass :class:`~surge_tpu.log.transport.LogRecord` per record (~2.8 µs
each) even though most consumers touch only a field or two. This module
provides __slots__ **views** that decode on access over buffers the native
layer indexed in one call:

- :class:`SegmentRecordView` — over an (uncompressed) segment block payload
  indexed by ``csrc/txn.cc surge_seg_index`` (every FileLog read and the
  resident plane's refresh feed ride this);
- :class:`WireRecordView` — over a serialized reply's bytes indexed by
  ``surge_reply_index`` (the gRPC client's Read/Transact reply legs);
- the lazy reply wrappers (:func:`lazy_read_reply` / :func:`lazy_txn_reply`)
  the client registers as response deserializers when the native layer is
  built, falling back to the protobuf classes otherwise.

Contract: a view is **observably identical** to the LogRecord the pre-view
path built — equality (both directions), ``repr``, field values, tombstone
``None`` semantics — enforced by tests/test_reply_views.py. Fallback stays
bit-identical: with the library unbuilt or ``surge.log.native.enabled=false``
every caller takes the original LogRecord/protobuf path.

:func:`py_reply_format` is the pure-Python twin of ``surge_reply_format``
(canonical proto3 bytes: fields in number order, defaults skipped, headers
in sorted key order) — the property test asserts bit-identity.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional

from surge_tpu.log.transport import LogRecord

__all__ = [
    "SegmentRecordView", "WireRecordView", "lazy_read_reply",
    "lazy_txn_reply", "materialize", "py_reply_format",
    "records_from_reply",
]

_UNSET = object()


def _uvarint(data, pos: int):
    shift = n = 0
    while True:
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


class _RecordViewBase:
    """Field-wise equality/repr shared by every view flavor. Comparison with
    a real LogRecord works BOTH directions: the dataclass ``__eq__`` answers
    NotImplemented for a foreign class, so Python reflects into ours."""

    __slots__ = ()

    def __eq__(self, other):
        if isinstance(other, (_RecordViewBase, LogRecord)):
            return (self.offset == other.offset
                    and self.partition == other.partition
                    and self.key == other.key
                    and self.value == other.value
                    and self.topic == other.topic
                    and self.timestamp == other.timestamp
                    and dict(self.headers) == dict(other.headers))
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    # LogRecord itself is unhashable at runtime (its generated __hash__
    # raises on the headers dict); match that contract
    __hash__ = None

    def __repr__(self) -> str:  # the dataclass repr, verbatim
        return (f"LogRecord(topic={self.topic!r}, key={self.key!r}, "
                f"value={self.value!r}, partition={self.partition!r}, "
                f"headers={dict(self.headers)!r}, offset={self.offset!r}, "
                f"timestamp={self.timestamp!r})")


def materialize(record) -> LogRecord:
    """A real LogRecord from any record-shaped object (view or LogRecord) —
    for callers that genuinely need the frozen dataclass."""
    if isinstance(record, LogRecord):
        return record
    return LogRecord(topic=record.topic, key=record.key, value=record.value,
                     partition=record.partition,
                     headers=dict(record.headers), offset=record.offset,
                     timestamp=record.timestamp)


class SegmentRecordView(_RecordViewBase):
    """One record over a segment block payload + its native index row
    (``surge_seg_index``: [flags, key_off, key_len, val_off, val_len,
    hdr_off, hdr_cnt] at ``rows[o:o+7]``). key/value/headers decode on first
    access and stay cached; the payload is shared by every record of the
    block."""

    __slots__ = ("_payload", "_rows", "_o", "topic", "partition", "offset",
                 "timestamp", "_key", "_value", "_headers")

    def __init__(self, payload, rows, o: int, topic: str, partition: int,
                 offset: int, timestamp: float) -> None:
        self._payload = payload
        self._rows = rows
        self._o = o
        self.topic = topic
        self.partition = partition
        self.offset = offset
        self.timestamp = timestamp
        self._key = _UNSET
        self._value = _UNSET
        self._headers = _UNSET

    @property
    def key(self) -> Optional[str]:
        k = self._key
        if k is _UNSET:
            rows, o = self._rows, self._o
            k = (self._payload[rows[o + 1]: rows[o + 1] + rows[o + 2]]
                 .decode() if rows[o] & 1 else None)
            self._key = k
        return k

    @property
    def value(self) -> Optional[bytes]:
        v = self._value
        if v is _UNSET:
            rows, o = self._rows, self._o
            v = (self._payload[rows[o + 3]: rows[o + 3] + rows[o + 4]]
                 if not rows[o] & 2 else None)
            self._value = v
        return v

    @property
    def headers(self) -> Dict[str, str]:
        h = self._headers
        if h is _UNSET:
            rows, o = self._rows, self._o
            h = {}
            nh = rows[o + 6]
            if nh:
                payload = self._payload
                pos = rows[o + 5]
                for _ in range(nh):
                    hklen, pos = _uvarint(payload, pos)
                    hk = payload[pos: pos + hklen].decode()
                    pos += hklen
                    hvlen, pos = _uvarint(payload, pos)
                    h[hk] = payload[pos: pos + hvlen].decode()
                    pos += hvlen
            self._headers = h
        return h


class WireRecordView(_RecordViewBase):
    """One record over a serialized reply's bytes + its native index row
    (``surge_reply_index``: [flags, topic_off, topic_len, key_off, key_len,
    val_off, val_len, partition, offset, hdr_cnt, msg_off, msg_len] at
    ``rows[o:o+12]``). Everything string/bytes decodes on access; headers
    re-walk only this record's message slice, and only when touched."""

    __slots__ = ("_buf", "_rows", "_o", "timestamp", "_topic", "_key",
                 "_value", "_headers")

    def __init__(self, buf: bytes, rows, o: int, timestamp: float) -> None:
        self._buf = buf
        self._rows = rows
        self._o = o
        self.timestamp = timestamp
        self._topic = _UNSET
        self._key = _UNSET
        self._value = _UNSET
        self._headers = _UNSET

    @property
    def topic(self) -> str:
        t = self._topic
        if t is _UNSET:
            rows, o = self._rows, self._o
            t = self._buf[rows[o + 1]: rows[o + 1] + rows[o + 2]].decode()
            self._topic = t
        return t

    @property
    def key(self) -> Optional[str]:
        k = self._key
        if k is _UNSET:
            rows, o = self._rows, self._o
            k = (self._buf[rows[o + 3]: rows[o + 3] + rows[o + 4]].decode()
                 if rows[o] & 1 else None)
            self._key = k
        return k

    @property
    def value(self) -> Optional[bytes]:
        v = self._value
        if v is _UNSET:
            rows, o = self._rows, self._o
            v = (self._buf[rows[o + 5]: rows[o + 5] + rows[o + 6]]
                 if not rows[o] & 2 else None)
            self._value = v
        return v

    @property
    def partition(self) -> int:
        return self._rows[self._o + 7]

    @property
    def offset(self) -> int:
        return self._rows[self._o + 8]

    @property
    def headers(self) -> Dict[str, str]:
        h = self._headers
        if h is _UNSET:
            h = {}
            rows, o = self._rows, self._o
            if rows[o + 9]:
                buf = self._buf
                pos = rows[o + 10]
                end = pos + rows[o + 11]
                while pos < end:
                    tag, pos = _uvarint(buf, pos)
                    if tag == 0x3A:  # field 7, len-delimited: one map entry
                        ent_len, pos = _uvarint(buf, pos)
                        ent_end = pos + ent_len
                        hk = hv = ""
                        while pos < ent_end:
                            etag, pos = _uvarint(buf, pos)
                            elen, pos = _uvarint(buf, pos)
                            if etag == 0x0A:
                                hk = buf[pos: pos + elen].decode()
                            elif etag == 0x12:
                                hv = buf[pos: pos + elen].decode()
                            pos += elen
                        h[hk] = hv
                    else:
                        pos = _skip_field(buf, pos, tag & 7)
            self._headers = h
        return h


def _skip_field(buf: bytes, pos: int, wt: int) -> int:
    if wt == 0:
        _, pos = _uvarint(buf, pos)
        return pos
    if wt == 1:
        return pos + 8
    if wt == 2:
        n, pos = _uvarint(buf, pos)
        return pos + n
    if wt == 5:
        return pos + 4
    raise ValueError(f"unknown wire type {wt}")


def records_from_reply(data: bytes, field: int) -> Optional[List[WireRecordView]]:
    """Every RecordMsg of the reply's repeated ``field`` as lazy views, or
    None (library unbuilt / bytes the indexer refuses — callers protobuf-
    parse instead)."""
    from surge_tpu.log import native_gate

    idx = native_gate.reply_index(data, field)
    if idx is None:
        return None
    rows, ts = idx
    width = native_gate.REPLY_ROW_WIDTH
    return [WireRecordView(data, rows, i * width, ts[i])
            for i in range(len(ts))]


class _LazyReadReply:
    """ReadReply twin: just the records, as views."""

    __slots__ = ("records",)

    def __init__(self, records: List[WireRecordView]) -> None:
        self.records = records


class _LazyTxnReply:
    """TxnReply twin: scalar fields parsed once with a tiny wire walk (a
    handful of fields per reply), records as lazy views."""

    __slots__ = ("ok", "error", "error_kind", "leader_hint", "records")

    def __init__(self, data: bytes, records: List[WireRecordView]) -> None:
        self.ok = False
        self.error = ""
        self.error_kind = ""
        self.leader_hint = ""
        self.records = records
        pos = 0
        n = len(data)
        while pos < n:
            tag, pos = _uvarint(data, pos)
            field = tag >> 3
            if field == 1 and tag & 7 == 0:
                v, pos = _uvarint(data, pos)
                self.ok = bool(v)
            elif field in (2, 3, 5) and tag & 7 == 2:
                slen, pos = _uvarint(data, pos)
                s = data[pos: pos + slen].decode()
                pos += slen
                if field == 2:
                    self.error = s
                elif field == 3:
                    self.error_kind = s
                else:
                    self.leader_hint = s
            else:
                pos = _skip_field(data, pos, tag & 7)


def lazy_read_reply(data: bytes):
    """Client response deserializer for Read: lazy views over the reply
    bytes via one native index call; protobuf parse when native is off."""
    recs = records_from_reply(data, 1)
    if recs is None:
        from surge_tpu.log import log_service_pb2 as pb

        return pb.ReadReply.FromString(data)
    return _LazyReadReply(recs)


def lazy_txn_reply(data: bytes):
    """Client response deserializer for Transact (TxnReply.records is
    field 4)."""
    recs = records_from_reply(data, 4)
    if recs is None:
        from surge_tpu.log import log_service_pb2 as pb

        return pb.TxnReply.FromString(data)
    return _LazyTxnReply(data, recs)


# -- pure-Python reply-format twin (fallback + property-test reference) -----


def _py_uvarint(buf: bytearray, n: int) -> None:
    while n >= 0x80:
        buf.append((n & 0x7F) | 0x80)
        n >>= 7
    buf.append(n & 0x7F)


def py_reply_format(records, field: int) -> bytes:
    """The canonical serialized repeated-RecordMsg bytes ``csrc/txn.cc
    surge_reply_format`` emits, in pure Python: proto3 field order, defaults
    skipped, ``has_key``/``has_value`` as explicit presence bits, headers as
    map entries in SORTED key order. The property test asserts bit-identity
    against the native formatter; protobuf's own parser accepts either
    (field order and map order are reader-irrelevant)."""
    out = bytearray()
    rec_tag = (field << 3) | 2
    for r in records:
        msg = bytearray()
        tb = r.topic.encode("utf-8")
        if tb:
            msg.append(0x0A)
            _py_uvarint(msg, len(tb))
            msg += tb
        if r.key is not None:
            msg += b"\x10\x01"
            kb = r.key.encode("utf-8")
            if kb:
                msg.append(0x1A)
                _py_uvarint(msg, len(kb))
                msg += kb
        if r.value is not None:
            msg += b"\x20\x01"
            if r.value:
                msg.append(0x2A)
                _py_uvarint(msg, len(r.value))
                msg += r.value
        if r.partition:
            msg.append(0x30)
            _py_uvarint(msg, r.partition & 0xFFFFFFFFFFFFFFFF)
        for hk, hv in sorted(dict(r.headers).items()):
            ent = bytearray()
            hkb = hk.encode("utf-8")
            hvb = hv.encode("utf-8")
            if hkb:
                ent.append(0x0A)
                _py_uvarint(ent, len(hkb))
                ent += hkb
            if hvb:
                ent.append(0x12)
                _py_uvarint(ent, len(hvb))
                ent += hvb
            msg.append(0x3A)
            _py_uvarint(msg, len(ent))
            msg += ent
        if r.offset:
            msg.append(0x40)
            _py_uvarint(msg, r.offset & 0xFFFFFFFFFFFFFFFF)
        ts = struct.pack("<d", r.timestamp)
        if ts != b"\x00" * 8:
            msg.append(0x49)
            msg += ts
        _py_uvarint(out, rec_tag)
        _py_uvarint(out, len(msg))
        out += msg
    return bytes(out)
