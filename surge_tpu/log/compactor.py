"""Background log compaction — latest-record-per-key retention for compacted topics.

The reference's durable aggregate store IS a compacted Kafka topic (overview.md:8-63),
but until this module the repo only *marked* topics compacted and faked the compacted
view with a full-partition scan. This is the real cleaner, re-derived from Kafka's
LogCleaner in two layers:

- **Policy** (here): :func:`select_retained` picks the survivor set of one partition —
  the latest record per key, tombstones garbage-collected once they are older than the
  retention window (``delete.retention.ms`` analog: a tombstone must outlive slow
  consumers so they see the delete before it disappears), keyless control records
  (publisher flush markers) dropped, and the partition's final record always kept so
  the tail of the offset space stays readable. Offsets are never rewritten — a
  compacted partition is the same partition with holes, exactly like Kafka's.
- **Mechanics** (per backend): ``InMemoryLog.compact_partition`` swaps the record
  list; ``FileLog.compact_partition`` rewrites the segment file crash-safely
  (tmp write → fsync → rename → recovery-manifest update, surge_tpu.log.file).

:class:`LogCompactor` is the scheduler: a health-bus supervised
:class:`~surge_tpu.common.BackgroundTask` that wakes on an interval, measures each
compacted partition's **dirty ratio** — records appended since the last clean pass
over total live records, Kafka's ``min.cleanable.dirty.ratio`` — and compacts the
partitions above threshold. It is also directly triggerable (admin RPC
``CompactLog`` / ``tools/compact_log.py``) via :meth:`compact_once`.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from surge_tpu.common import Ack, BackgroundTask, Controllable, logger
from surge_tpu.config import Config, default_config
from surge_tpu.log.transport import LogRecord

__all__ = ["CompactionStats", "LogCompactor", "dirty_ratio", "select_retained"]


@dataclass(frozen=True)
class CompactionStats:
    """Outcome of one partition compaction pass."""

    topic: str
    partition: int
    records_before: int
    records_after: int
    bytes_before: int
    bytes_after: int
    tombstones_dropped: int
    duration_s: float

    @property
    def bytes_reclaimed(self) -> int:
        return max(self.bytes_before - self.bytes_after, 0)

    @property
    def records_dropped(self) -> int:
        return self.records_before - self.records_after

    def as_dict(self) -> dict:
        return {
            "topic": self.topic, "partition": self.partition,
            "records_before": self.records_before,
            "records_after": self.records_after,
            "bytes_before": self.bytes_before, "bytes_after": self.bytes_after,
            "bytes_reclaimed": self.bytes_reclaimed,
            "tombstones_dropped": self.tombstones_dropped,
            "duration_s": self.duration_s,
        }


def select_retained(records: Sequence[LogRecord], *, now: float,
                    tombstone_retention_s: float = 0.0
                    ) -> Tuple[List[LogRecord], int]:
    """The survivor set of one partition, in offset order.

    Keeps the latest record per key; a tombstone survives only while younger
    than ``tombstone_retention_s`` (0 = GC immediately); keyless records are
    dropped (control markers — consumers skip them); the final record is
    always kept so reads from the tail still return data and recovery can
    re-derive the frontier from the last block. Returns
    ``(retained, tombstones_dropped)``.
    """
    latest: Dict[str, LogRecord] = {}
    for r in records:
        if r.key is not None:
            latest[r.key] = r
    keep: set = set()
    expired_tombstones: set = set()
    for r in latest.values():
        if r.value is None and now - r.timestamp >= tombstone_retention_s:
            expired_tombstones.add(r.offset)
            continue
        keep.add(r.offset)
    if records:
        keep.add(records[-1].offset)  # may resurrect an expired tail tombstone
    return ([r for r in records if r.offset in keep],
            len(expired_tombstones - keep))


def dirty_ratio(log, topic: str, partition: int) -> float:
    """Records appended since the last clean pass over total live records —
    Kafka's ``min.cleanable.dirty.ratio`` input. 1.0 for a never-compacted
    non-empty partition, 0.0 for an empty or just-compacted one."""
    state = log.compaction_state(topic, partition)
    end = log.end_offset(topic, partition)
    dirty = max(end - state["clean_end"], 0)
    live = state["clean_count"] + dirty
    return dirty / live if live else 0.0


class LogCompactor(Controllable):
    """Dirty-ratio-driven compaction scheduler over one log's compacted topics.

    Config knobs (docs/compaction.md):

    - ``surge.log.compaction.interval-ms`` — scheduler wake cadence.
    - ``surge.log.compaction.min-dirty-ratio`` — compact partitions at/above.
    - ``surge.log.compaction.min-dirty-records`` — skip partitions with fewer
      new records than this regardless of ratio (tiny partitions churn).
    - ``surge.log.compaction.tombstone-retention-ms`` — tombstone GC window.
    """

    health_name = "log-compactor"

    def __init__(self, log, config: Config | None = None,
                 topics: Optional[Sequence[str]] = None, metrics=None,
                 on_signal: Callable[[str, str], None] | None = None) -> None:
        self.log = log
        self.config = config or default_config()
        self.topics = list(topics) if topics is not None else None
        self.metrics = metrics  # EngineMetrics quiver (optional)
        self.on_signal = on_signal or (lambda name, level: None)
        self._interval_s = self.config.get_seconds(
            "surge.log.compaction.interval-ms", 30_000)
        self._min_ratio = self.config.get_float(
            "surge.log.compaction.min-dirty-ratio", 0.5)
        self._min_records = self.config.get_int(
            "surge.log.compaction.min-dirty-records", 64)
        self._tombstone_retention_s = self.config.get_seconds(
            "surge.log.compaction.tombstone-retention-ms", 60_000)
        self._task = BackgroundTask(self._loop, "log-compactor")
        self.total_stats: List[CompactionStats] = []  # most-recent-first, capped

    # -- lifecycle ----------------------------------------------------------------------

    async def start(self) -> Ack:
        self._task.start()
        return Ack()

    async def stop(self) -> Ack:
        await self._task.stop()
        return Ack()

    @property
    def running(self) -> bool:
        return self._task.running

    # -- scheduling ---------------------------------------------------------------------

    def _compacted_partitions(self, topic: Optional[str] = None):
        """(topic, partition) pairs eligible for compaction. Lookups are
        NON-mutating — ``log.topic()`` would auto-create, so a mistyped
        operator topic (admin RPC / CLI) must resolve to nothing, not to a
        freshly persisted junk topic."""
        known = getattr(self.log, "_topics", {})
        names = ([topic] if topic else
                 (self.topics if self.topics is not None else sorted(known)))
        for name in names:
            spec = known.get(name)
            if spec is None or not spec.compacted:
                continue
            for p in range(spec.partitions):
                yield name, p

    def _eligible(self, topic: str, p: int) -> bool:
        state = self.log.compaction_state(topic, p)
        dirty = max(self.log.end_offset(topic, p) - state["clean_end"], 0)
        return (dirty >= self._min_records
                and dirty_ratio(self.log, topic, p) >= self._min_ratio)

    async def compact_once(self, topic: Optional[str] = None,
                           force: bool = False) -> List[CompactionStats]:
        """One full pass (the admin-RPC / CLI entry): compact every eligible
        compacted partition — all of them when ``force`` (operator-triggered
        compaction must not argue about ratios). File IO runs in the default
        executor so the event loop never blocks on a segment rewrite."""
        out: List[CompactionStats] = []
        if not hasattr(self.log, "compact_partition"):
            return out  # e.g. a remote LogClient: compaction is broker-side
        loop = asyncio.get_running_loop()
        for name, p in list(self._compacted_partitions(topic)):
            if not force and not self._eligible(name, p):
                continue
            stats = await loop.run_in_executor(
                None, lambda name=name, p=p: self.log.compact_partition(
                    name, p,
                    tombstone_retention_s=self._tombstone_retention_s))
            out.append(stats)
            self._record(stats)
        return out

    def _record(self, stats: CompactionStats) -> None:
        self.total_stats.insert(0, stats)
        del self.total_stats[64:]
        logger.info(
            "compacted %s[%d]: %d -> %d records, %d bytes reclaimed (%.3fs)",
            stats.topic, stats.partition, stats.records_before,
            stats.records_after, stats.bytes_reclaimed, stats.duration_s)
        if self.metrics is not None:
            self.metrics.compaction_runs.record()
            self.metrics.compaction_bytes_reclaimed.record(stats.bytes_reclaimed)
            self.metrics.compaction_records_dropped.record(stats.records_dropped)
            self.metrics.compaction_timer.record_ms(stats.duration_s * 1000.0)

    async def _loop(self) -> None:
        # same unkillable-loop discipline as the indexer tail: a failing
        # compaction pass (disk full, transient IO error) must log + signal and
        # retry next interval, never end the task silently
        while True:
            await asyncio.sleep(self._interval_s)
            try:
                if not hasattr(self.log, "compact_partition"):
                    continue  # e.g. a remote LogClient: compaction is broker-side
                if self.metrics is not None:
                    ratios = [dirty_ratio(self.log, t, p)
                              for t, p in self._compacted_partitions()]
                    self.metrics.compaction_max_dirty_ratio.record(
                        max(ratios, default=0.0))
                await self.compact_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — keep the scheduler alive
                logger.exception("compaction pass failed; retrying in %.1fs",
                                 self._interval_s)
                try:
                    self.on_signal("surge.log.compaction-error", "error")
                except Exception:  # noqa: BLE001
                    logger.exception("on_signal failed")
