"""Chained per-partition log digests — the cross-replica integrity sensor.

One :class:`DigestIndex` per log backend maintains, for every audited
``(topic, partition)``, a CRC-chained rolling digest over the canonical bytes
of each record (offset, key, value — timestamps are excluded: the eager
in-memory record and the segment-decoded read-back may round-trip a float
timestamp differently on the two replicas of a byte-identical log, and the
digest must only move when the *replicated* bytes do). Because the chain folds
record-by-record, batch boundaries don't matter: a leader that committed in
one batch and a follower that ingested the same records across three ships
compute the same digest at the same offset.

Maintenance is **hybrid eager + lazy**, and always incremental:

- *eager*: the backends call :meth:`DigestIndex.observe` from their append /
  verbatim-ingest finish paths (outside the log lock) with the just-landed
  records; records contiguous with the chain head fold immediately, anything
  else is skipped and left to catch-up — out-of-order delivery can only make
  the chain lazier, never wrong.
- *lazy*: :meth:`digest_at` reads ``[chained, upto)`` from the log and folds
  the delta forward. This covers the broker's native Transact path
  (``_append_batch_locked`` never materializes LogRecords) at the cost of one
  bounded read of *new* records per query — never a full-partition rescan.

Checkpoints — ``(offset, digest)`` pairs pushed every ``checkpoint_every``
records — bound the cost of a query *below* the chain head (a follower asked
at the leader's smaller high-watermark): re-chain from the nearest checkpoint
at or under ``upto`` instead of from the base.

Compaction resets the whole chain to the new clean frontier (retention-time
GC makes compacted prefixes replica-divergent by design; the replication
compaction barrier compacts the same prefix on leader and follower, so both
sides reset to the same ``base`` and stay comparable above it). Truncation
(KIP-101 divergent-tail drop) rolls the chain back to the best surviving
checkpoint. A digest query answers ``digest: None`` with its ``base`` when
``upto`` falls below the comparable region — the auditor treats unequal bases
as *incomparable*, never as a mismatch.
"""

from __future__ import annotations

import struct
import threading
import zlib
from bisect import bisect_right, insort
from typing import Dict, List, Optional, Tuple

__all__ = ["DigestIndex", "fold_record", "CHAIN_SEED"]

#: the chain seed — digest of the empty prefix ``[base, base)``
CHAIN_SEED = 0

_CANON = struct.Struct("<qII")  # offset, key-len, value-len


def fold_record(crc: int, record) -> int:
    """Fold one record's canonical bytes into the chain: a length-framed
    (offset, key, value) triple through ``zlib.crc32``. Headers and
    timestamps are deliberately outside the canon (module doc)."""
    key = record.key
    kb = key.encode("utf-8") if isinstance(key, str) else (key or b"")
    vb = record.value or b""
    crc = zlib.crc32(_CANON.pack(record.offset, len(kb), len(vb)), crc)
    crc = zlib.crc32(kb, crc)
    return zlib.crc32(vb, crc) & 0xFFFFFFFF


class _Chain:
    """One partition's rolling digest state."""

    __slots__ = ("base", "chained", "head", "checkpoints")

    def __init__(self, base: int) -> None:
        self.base = base          # offsets below are not digestable
        self.chained = base       # next offset to fold
        self.head = CHAIN_SEED    # digest over [base, chained)
        #: sorted (offset, digest-over-[base, offset)) pairs
        self.checkpoints: List[Tuple[int, int]] = []


class DigestIndex:
    """Per-partition chained digests over one log backend (module doc)."""

    def __init__(self, log, *, checkpoint_every: int = 256,
                 max_checkpoints: int = 64) -> None:
        self._log = log
        self._every = max(int(checkpoint_every), 1)
        self._max_cks = max(int(max_checkpoints), 1)
        self._chains: Dict[Tuple[str, int], _Chain] = {}
        self._lock = threading.Lock()
        self.stats = {"eager_records": 0, "catchup_records": 0,
                      "refold_records": 0, "resets": 0, "rollbacks": 0}

    # -- chain bookkeeping --------------------------------------------------------------

    def _chain(self, topic: str, partition: int) -> _Chain:
        key = (topic, partition)
        ch = self._chains.get(key)
        if ch is None:
            # a chain created over pre-existing records anchors at the clean
            # frontier: compacted prefixes are replica-divergent by design
            try:
                base = int(self._log.compaction_state(
                    topic, partition)["clean_end"])
            except Exception:  # noqa: BLE001 — backend without compaction
                base = 0
            ch = self._chains[key] = _Chain(base)
        return ch

    def _push_checkpoint(self, ch: _Chain, offset: int, digest: int) -> None:
        if ch.checkpoints and ch.checkpoints[-1][0] >= offset:
            if not any(c[0] == offset for c in ch.checkpoints):
                insort(ch.checkpoints, (offset, digest))
        else:
            ch.checkpoints.append((offset, digest))
        if len(ch.checkpoints) > self._max_cks:
            del ch.checkpoints[0: len(ch.checkpoints) - self._max_cks]

    def _fold_forward(self, ch: _Chain, records, counter: str) -> None:
        """Fold records (offset order, all >= ch.chained) into the head."""
        for r in records:
            ch.head = fold_record(ch.head, r)
            ch.chained = r.offset + 1
            self.stats[counter] += 1
            if ch.chained % self._every == 0:
                self._push_checkpoint(ch, ch.chained, ch.head)

    # -- eager maintenance (append/verbatim-ingest hooks) -------------------------------

    def observe(self, records) -> None:
        """Fold just-appended records. Only runs when a run is contiguous
        with its partition's chain head — anything else (out-of-order finish
        delivery, replica gap slices, records landed before the index
        existed) is left to the lazy catch-up in :meth:`digest_at`. Called
        OUTSIDE the log lock (digest-lock → log-lock is the one permitted
        ordering; see ``digest_at``)."""
        with self._lock:
            for r in records:
                ch = self._chain(r.topic, r.partition)
                if r.offset != ch.chained:
                    continue
                self._fold_forward(ch, (r,), "eager_records")

    # -- queries ------------------------------------------------------------------------

    def digest_at(self, topic: str, partition: int, upto: int) -> dict:
        """The digest over ``[base, upto)``. The caller must clamp ``upto``
        to the partition's durable end offset (``LogBase.partition_digest``
        does) — folding past the end would mark unseen records as chained.
        Returns ``{"topic", "partition", "upto", "base", "chained",
        "digest"}``; ``digest`` is None (with ``base`` for the caller's
        comparability check) when ``upto`` is below the chain base."""
        with self._lock:
            ch = self._chain(topic, partition)
            out = {"topic": topic, "partition": partition, "upto": upto,
                   "base": ch.base}
            if upto < ch.base:
                out.update(digest=None, chained=ch.chained)
                return out
            if upto >= ch.chained:
                if upto > ch.chained:  # lazy catch-up: fold the delta only
                    self._fold_forward(
                        ch, self._read_range(topic, partition, ch.chained,
                                             upto), "catchup_records")
                    ch.chained = upto
                digest = ch.head
                self._push_checkpoint(ch, upto, digest)
            else:
                digest = self._refold_below(ch, topic, partition, upto)
            out.update(digest=f"{digest:08x}", chained=ch.chained)
            return out

    def _refold_below(self, ch: _Chain, topic: str, partition: int,
                      upto: int) -> int:
        """Digest at an offset below the chain head: re-chain from the
        nearest checkpoint at/under ``upto`` (or the base). Does not move
        the chain; caches the answer as a checkpoint."""
        i = bisect_right(ch.checkpoints, (upto, 0xFFFFFFFF)) - 1
        if i >= 0:
            start, digest = ch.checkpoints[i]
        else:
            start, digest = ch.base, CHAIN_SEED
        if start < upto:
            for r in self._read_range(topic, partition, start, upto):
                digest = fold_record(digest, r)
                self.stats["refold_records"] += 1
        self._push_checkpoint(ch, upto, digest)
        return digest

    def _read_range(self, topic: str, partition: int, lo: int, hi: int):
        """Records with ``lo <= offset < hi`` in offset order, paged (the
        catch-up after a native-path burst must not materialize the whole
        delta at once)."""
        while lo < hi:
            page = self._log.read(topic, partition, from_offset=lo,
                                  max_records=min(hi - lo, 2048))
            if not page:
                return
            for r in page:
                if r.offset >= hi:
                    return
                yield r
            lo = page[-1].offset + 1

    # -- rewrite hooks ------------------------------------------------------------------

    def on_compact(self, topic: str, partition: int, frontier: int) -> None:
        """Compaction rewrote ``[.., frontier)``: reset the chain to the new
        clean base. Leader and follower run the compaction barrier over the
        same prefix, so both reset to the same base and digests above it
        stay comparable."""
        with self._lock:
            key = (topic, partition)
            if key in self._chains or frontier > 0:
                self._chains[key] = _Chain(max(frontier, 0))
                self.stats["resets"] += 1

    def on_truncate(self, topic: str, partition: int, to_offset: int) -> None:
        """Failover truncation dropped offsets >= ``to_offset``: roll the
        chain back to the best surviving checkpoint (or the base — a full
        re-chain from there is lazy and bounded by the surviving prefix)."""
        with self._lock:
            ch = self._chains.get((topic, partition))
            if ch is None or ch.chained <= to_offset:
                return
            ch.checkpoints = [c for c in ch.checkpoints if c[0] <= to_offset]
            if ch.checkpoints:
                ch.chained, ch.head = ch.checkpoints[-1]
            else:
                ch.chained, ch.head = ch.base, CHAIN_SEED
            self.stats["rollbacks"] += 1

    def snapshot(self) -> dict:
        """Counters + per-partition chain positions (observability)."""
        with self._lock:
            chains = {f"{t}[{p}]": {"base": c.base, "chained": c.chained,
                                    "checkpoints": len(c.checkpoints)}
                      for (t, p), c in self._chains.items()}
            return {"stats": dict(self.stats), "chains": chains}
